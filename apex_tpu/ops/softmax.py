"""Fused scale+mask+softmax — TPU rebuild of the Megatron kernels
``csrc/megatron/scaled_masked_softmax_cuda.cu``,
``scaled_upper_triang_masked_softmax_cuda.cu`` and the generic fallback.

On TPU the scale→mask→softmax chain is a single VPU-friendly fusion that XLA
performs reliably; the custom_vjp here reproduces the CUDA kernels' *memory*
behavior — the backward uses only the saved softmax output
(``dx = (dy - Σ dy·y) · y · scale``), never the logits — which is the actual
win of the fused kernel.  Unlike the CUDA kernels there is no seq≤4K
template limit.

Masks follow apex conventions: boolean mask with True = masked-out
(filled with -10000 before softmax), or the causal (upper-triangular)
variant with no materialized mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_f32 = jnp.float32
MASK_FILL = -10000.0


def _softmax_last(x):
    x = x - jax.lax.stop_gradient(jnp.max(x, axis=-1, keepdims=True))
    ex = jnp.exp(x)
    return ex / jnp.sum(ex, axis=-1, keepdims=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _scaled_masked_softmax(x, mask, scale):
    y, _ = _sms_fwd(x, mask, scale)
    return y


def _sms_fwd(x, mask, scale):
    xs = x.astype(_f32) * scale
    if mask is not None:
        xs = jnp.where(mask, MASK_FILL, xs)
    y = _softmax_last(xs).astype(x.dtype)
    return y, (y,)


def _sms_bwd(scale, res, dy):
    (y,) = res
    yf = y.astype(_f32)
    dyf = dy.astype(_f32)
    dx = (dyf - jnp.sum(dyf * yf, axis=-1, keepdims=True)) * yf * scale
    return dx.astype(dy.dtype), None


_scaled_masked_softmax.defvjp(_sms_fwd, _sms_bwd)


def scaled_masked_softmax(x, mask, scale=1.0):
    """``softmax(scale*x masked_fill(mask, -10000))`` over the last axis.

    x: ``(b, np, sq, sk)`` attention scores; mask: broadcastable boolean,
    True = masked (apex ``ScaledMaskedSoftmax``).
    """
    return _scaled_masked_softmax(x, mask, float(scale))


def scaled_softmax(x, scale=1.0):
    """No-mask variant (apex ``ScaledSoftmax``)."""
    return _scaled_masked_softmax(x, None, float(scale))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scaled_upper_triang_masked_softmax(x, scale=1.0):
    """Causal softmax for ``(b, sq, sk)`` scores (apex
    ``ScaledUpperTriangMaskedSoftmax``): position q attends to k ≤ q."""
    y, _ = _sutms_fwd(x, scale)
    return y


def _causal_mask(sq, sk):
    q = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return k > q + (sk - sq)


def _sutms_fwd(x, scale):
    sq, sk = x.shape[-2], x.shape[-1]
    xs = x.astype(_f32) * scale
    xs = jnp.where(_causal_mask(sq, sk), MASK_FILL, xs)
    y = _softmax_last(xs).astype(x.dtype)
    return y, (y,)


def _sutms_bwd(scale, res, dy):
    (y,) = res
    yf = y.astype(_f32)
    dyf = dy.astype(_f32)
    dx = (dyf - jnp.sum(dyf * yf, axis=-1, keepdims=True)) * yf * scale
    return (dx.astype(dy.dtype),)


scaled_upper_triang_masked_softmax.defvjp(_sutms_fwd, _sutms_bwd)


def generic_scaled_masked_softmax(x, mask, scale=1.0):
    """Arbitrary-shape fallback (apex ``generic_scaled_masked_softmax``)."""
    return _scaled_masked_softmax(x, mask, float(scale))
