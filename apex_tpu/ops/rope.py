"""Fused rotary positional embedding — TPU rebuild of
``csrc/megatron/fused_rotary_positional_embedding_cuda.cu`` +
``apex/transformer/functional/fused_rope.py``.

The rotate-half formulation is a pure VPU elementwise pattern that XLA fuses
into adjacent ops; the custom_vjp mirrors the CUDA kernel's analytic
backward (rotation by -θ) instead of differentiating through sin/cos, so
``freqs`` never receives a gradient (apex treats it as non-differentiable).

Layouts follow apex: ``sbhd`` — ``(seq, batch, head, dim)`` — is the
default; ``thd`` (packed varlen with cu_seqlens) and the cached-sin/cos
variant are provided.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_f32 = jnp.float32


def _rotate_half(t):
    d = t.shape[-1] // 2
    t1, t2 = t[..., :d], t[..., d:]
    return jnp.concatenate([-t2, t1], axis=-1)


def _apply(t, cos, sin):
    rot_dim = cos.shape[-1]
    t_rot, t_pass = t[..., :rot_dim], t[..., rot_dim:]
    out = t_rot.astype(_f32) * cos + _rotate_half(t_rot.astype(_f32)) * sin
    out = out.astype(t.dtype)
    if t_pass.shape[-1]:
        out = jnp.concatenate([out, t_pass], axis=-1)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _rope_sbhd(t, cos, sin):
    return _apply(t, cos, sin)


def _rope_fwd(t, cos, sin):
    return _apply(t, cos, sin), (cos, sin)


def _rope_bwd(res, dy):
    cos, sin = res
    # y = t·cos + R(t)·sin with R = rotate-half ⇒ dt = dy·cos + Rᵀ(dy)·sin,
    # Rᵀ([v1, v2]) = [v2, -v1] — the CUDA kernel's analytic backward.
    rot_dim = cos.shape[-1]
    dy_rot, dy_pass = dy[..., :rot_dim], dy[..., rot_dim:]
    d = rot_dim // 2
    dy1, dy2 = dy_rot[..., :d].astype(_f32), dy_rot[..., d:].astype(_f32)
    rot_t = jnp.concatenate([dy2, -dy1], axis=-1)
    dx = (dy_rot.astype(_f32) * cos + rot_t * sin).astype(dy.dtype)
    if dy_pass.shape[-1]:
        dx = jnp.concatenate([dx, dy_pass], axis=-1)
    return dx, None, None


_rope_sbhd.defvjp(_rope_fwd, _rope_bwd)


def fused_apply_rotary_pos_emb(t, freqs, transpose_output_memory=False):
    """Apply RoPE to ``t`` of layout ``(seq, batch, head, dim)`` with
    ``freqs`` of shape ``(seq, 1, 1, rot_dim)`` (apex
    ``fused_apply_rotary_pos_emb``)."""
    del transpose_output_memory  # memory-format hint is meaningless on TPU
    f = freqs.astype(_f32)
    return _rope_sbhd(t, jnp.cos(f), jnp.sin(f))


def fused_apply_rotary_pos_emb_cached(t, cos_cached, sin_cached):
    """Variant taking precomputed cos/sin (apex ``..._cached``)."""
    return _rope_sbhd(t, cos_cached.astype(_f32), sin_cached.astype(_f32))


def fused_apply_rotary_pos_emb_thd(t, cu_seqlens, freqs):
    """Packed varlen layout ``(total_tokens, head, dim)`` where sequence i
    spans ``cu_seqlens[i]:cu_seqlens[i+1]`` — positions restart at each
    boundary (apex ``fused_apply_rotary_pos_emb_thd``)."""
    total = t.shape[0]
    positions = jnp.arange(total, dtype=jnp.int32)
    # position within sequence = index - start of its sequence
    seq_id = jnp.searchsorted(cu_seqlens, positions, side="right") - 1
    local_pos = positions - cu_seqlens[seq_id]
    f = freqs.astype(_f32)[local_pos]          # (total, 1, rot_dim)
    f = f.reshape(total, *([1] * (t.ndim - 2)), f.shape[-1])
    return _rope_sbhd(t, jnp.cos(f), jnp.sin(f))


def fused_apply_rotary_pos_emb_at_positions(t, cos_cached, sin_cached,
                                            positions):
    """Apply RoPE at explicit per-row positions — the decode-step form.

    ``t``: ``(batch, head, dim)`` (one token per sequence);
    ``cos_cached``/``sin_cached``: ``(max_seq, 1, 1, rot_dim)`` tables from
    :func:`rope_freqs`'s cos/sin; ``positions``: ``(batch,)`` int absolute
    positions.  During continuous batching every row sits at a different
    position, so the table is gathered per row instead of sliced by a
    shared offset.
    """
    rot_dim = cos_cached.shape[-1]
    cos = cos_cached.astype(_f32).reshape(-1, rot_dim)[positions]
    sin = sin_cached.astype(_f32).reshape(-1, rot_dim)[positions]
    cos = cos[:, None, :]                       # (batch, 1, rot_dim)
    sin = sin[:, None, :]
    return _apply(t, cos, sin)


def rope_freqs(seq_len, rot_dim, base=10000.0, dtype=_f32):
    """Standard RoPE frequency table ``(seq, 1, 1, rot_dim)``."""
    inv = 1.0 / (base ** (jnp.arange(0, rot_dim, 2, dtype=_f32) / rot_dim))
    t = jnp.arange(seq_len, dtype=_f32)
    f = jnp.outer(t, inv)
    f = jnp.concatenate([f, f], axis=-1)
    return f.reshape(seq_len, 1, 1, rot_dim).astype(dtype)
