"""Quantized int8 weight GEMM — the decode path's weight-bytes half.

PR 16 quantized the KV *cache* (scale-per-block int8,
``serving/paged_cache.py``); this op quantizes the *weights*.  At
batch-per-replica decode every linear in the step — qkv, out-proj,
fc1/fc2, the tied lm-head — is pure HBM bandwidth: the activation tile
is a handful of rows while the weight matrix streams through the MXU
once per token, so weight BYTES, not FLOPs, bound tokens/s.  Weights
are static across a serving process, so quantize once at load (the
EQuARX int8+scale idiom already proven here for KV blocks and
compressed collectives) and dequantize in-register inside the GEMM:

* :func:`quantize_weight`: per-OUTPUT-channel symmetric int8 over the
  ``(out_features, in_features)`` Megatron weight layout — one f32
  scale per row, ``scale = amax(|row|) / 127`` (an all-zero row gets
  scale 1.0 so the zeros round-trip exactly).  Round-to-nearest keeps
  the per-element error ``<= scale / 2``, and because the scale vector
  lives on the OUTPUT dim, slicing rows (the ColumnParallel /
  vocab-parallel shard direction) commutes BITWISE with quantization:
  shard-then-quantize == quantize-then-shard.  RowParallel weights
  shard the *input* dim, where per-shard quantization sees a local
  amax ``<=`` the full-row amax — per-shard scales are never larger,
  so the per-element error bound only tightens (tested, not assumed).
* :func:`quant_gemm`: ``y = x @ dequant(w8, scale)^T`` as one Pallas
  kernel — grid ``(n_blocks, k_blocks)`` with the contraction axis
  innermost; each step loads a ``(block_n, block_k)`` int8 weight tile
  (a quarter of the f32 bytes: the whole point), dequantizes it
  in-register against the ``(block_n, 1)`` scale column, and
  accumulates ``x_tile @ w_tile^T`` in f32 on the MXU
  (``preferred_element_type``) into a ``(m, block_n)`` VMEM scratch.
  Activations stay in their own dtype (bf16 keeps the full MXU rate).

Decode-only by design: there is no VJP — the quantized tree is built
once at inference-engine init (:func:`apex_tpu.models.gpt.
quantize_decode_params`) and the training entry points
(``pipeline_step``, ``GuardedTrainStep``, autotune) reject it.

Off-TPU the public API dispatches to :func:`quant_gemm_reference`,
which replays the EXACT dequantize-then-matmul op order (dequantize to
f32, cast to the activation dtype, the unfused linear's ``x @ w^T``) —
so the ``weight_quant`` model knob is deterministic off-chip and the
unit suite compares the kernel (interpret mode) against the reference
at the flash-attention tolerances.

Padding parity: zero-padded rows quantize to zero (scale 1.0 padding)
and zero-padded lanes contribute zero through the contraction, so
every extent pads to its block multiple inside the op and slices back
exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.multi_tensor_apply.bucketing import _round_up
from apex_tpu.utils.platform import (interpret_mode, tpu_compiler_params,
                                     use_pallas)

_f32 = jnp.float32

__all__ = ["quantize_weight", "dequantize_weight", "quant_gemm",
           "quant_gemm_reference"]


def _sds(shape, dtype, like):
    """vma-aware pallas output ShapeDtypeStruct (see
    :func:`apex_tpu.utils.collectives.sds_like`)."""
    from apex_tpu.utils.collectives import sds_like

    return sds_like(shape, dtype, like)


# ---------------------------------------------------------------------------
# quantize / dequantize
# ---------------------------------------------------------------------------

def quantize_weight(w):
    """``(out, in) -> (int8 (out, in), f32 (out,))`` per-output-channel
    symmetric quantization.

    ``scale[i] = max(|w[i, :]|) / 127`` (1.0 for an all-zero row, so
    zero weights survive the round trip bitwise); the stored value is
    ``round(w / scale)`` clipped to ``[-127, 127]``, which bounds the
    per-element reconstruction error by ``scale / 2``.  A pure
    function of the weight values — the same array quantizes to the
    same ``(w8, scale)`` bitwise on every load.
    """
    if w.ndim != 2:
        raise ValueError(f"quantize_weight expects a 2D (out, in) "
                         f"weight, got shape {w.shape}")
    w32 = jnp.asarray(w, _f32)
    amax = jnp.max(jnp.abs(w32), axis=1)
    scale = jnp.where(amax > 0.0, amax / 127.0,
                      jnp.ones_like(amax)).astype(_f32)
    q = jnp.clip(jnp.round(w32 / scale[:, None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def dequantize_weight(w8, scale):
    """``w8 * scale[:, None]`` in f32 — the reconstruction every
    consumer (kernel, reference, embedding gather) replays."""
    return w8.astype(_f32) * scale[:, None].astype(_f32)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _qgemm_kernel(x_ref, w_ref, s_ref, y_ref, acc_scr):
    ki = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    x = x_ref[:]
    # dequantize the int8 tile in-register: (block_n, block_k) f32,
    # then down to the activation dtype so the MXU runs at full rate
    w = (w_ref[:].astype(_f32) * s_ref[:].astype(_f32)).astype(x.dtype)
    # acc += x_tile @ w_tile^T, f32 accumulation on the MXU
    acc_scr[:] += jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                      preferred_element_type=_f32)

    @pl.when(ki == nk - 1)
    def _finish():
        y_ref[:] = acc_scr[:].astype(y_ref.dtype)


def _vmem(block, index_map):
    return pl.BlockSpec(block, index_map, memory_space=pltpu.VMEM)


def _pad2(a, r, c):
    if a.shape != (r, c):
        a = jnp.pad(a, ((0, r - a.shape[0]), (0, c - a.shape[1])))
    return a


def _qgemm_impl(x, w8, scale, block_n, block_k):
    """Pre-padded 2D operands: x (m_p, k_p), w8 (n_p, k_p) int8,
    scale (n_p, 1) f32; returns padded (m_p, n_p) f32."""
    m_p, k_p = x.shape
    n_p = w8.shape[0]
    nn, nk = n_p // block_n, k_p // block_k
    return pl.pallas_call(
        _qgemm_kernel,
        grid=(nn, nk),
        in_specs=[_vmem((m_p, block_k), lambda ni, ki: (0, ki)),
                  _vmem((block_n, block_k), lambda ni, ki: (ni, ki)),
                  _vmem((block_n, 1), lambda ni, ki: (ni, 0))],
        out_specs=_vmem((m_p, block_n), lambda ni, ki: (0, ni)),
        out_shape=_sds((m_p, n_p), _f32, x),
        scratch_shapes=[pltpu.VMEM((m_p, block_n), _f32)],
        compiler_params=tpu_compiler_params(("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(x, w8, scale)


# ---------------------------------------------------------------------------
# reference + public API
# ---------------------------------------------------------------------------

def quant_gemm_reference(x, w8, scale):
    """Unfused reference: the EXACT dequantize-then-matmul op order —
    reconstruct the f32 weight per output row, cast to the activation
    dtype (the unfused TP linear's GEMM contract), contract.  The
    off-TPU dispatch target, and what the kernel must match in
    interpret mode."""
    w = dequantize_weight(w8, scale)
    y = x @ w.astype(x.dtype).T
    return y.astype(_f32)


def _fit(requested, extent):
    """Largest candidate block <= requested dividing the lane-padded
    extent (the flash-attention block picker)."""
    padded = _round_up(extent, 128)
    for cand in (requested, 512, 384, 256, 128):
        if cand <= requested and padded % cand == 0:
            return cand
    return min(requested, padded)


def quant_gemm(x, w8, scale, *, block_n=512, block_k=512):
    """``x @ dequant(w8, scale)^T`` over ``(..., k)``; returns f32
    ``(..., out)`` (the decode heads' accumulation dtype).

    ``w8`` is int8 ``(out_features, in_features)`` with ``scale`` f32
    ``(out_features,)`` from :func:`quantize_weight` — the TP linear
    layout, so a row-block (ColumnParallel) or column-block
    (RowParallel) weight shard drops in per-rank unchanged with its
    per-shard scales.  Off-TPU (``use_pallas() == False``) dispatches
    to :func:`quant_gemm_reference`, which replays the dequantize →
    cast → matmul op order exactly.
    """
    if w8.dtype != jnp.int8:
        raise ValueError(f"w8 must be int8, got {w8.dtype}")
    if x.shape[-1] != w8.shape[1]:
        raise ValueError(f"x features {x.shape[-1]} != w8 in-dim "
                         f"{w8.shape[1]}")
    if scale.shape != (w8.shape[0],):
        raise ValueError(f"scale shape {scale.shape} != "
                         f"({w8.shape[0]},)")
    lead = x.shape[:-1]
    x2 = x.reshape((-1, x.shape[-1]))
    if not use_pallas():
        y = quant_gemm_reference(x2, w8, scale)
        return y.reshape(lead + (w8.shape[0],))
    m, k = x2.shape
    n = w8.shape[0]
    block_n = _fit(int(block_n), n)
    block_k = _fit(int(block_k), k)
    m_p = _round_up(m, 8)
    k_p = _round_up(k, block_k)
    n_p = _round_up(n, block_n)
    y = _qgemm_impl(_pad2(x2, m_p, k_p), _pad2(w8, n_p, k_p),
                    _pad2(scale[:, None].astype(_f32), n_p, 1),
                    block_n, block_k)
    return y[:m, :n].reshape(lead + (n,))
