"""Fused bias-GELU FFN — the transformer MLP pair as one Pallas op.

The reference ships this block as ``apex/fused_dense`` (CUDA cublasLt
epilogue GEMMs: ``Linear -> bias -> GELU`` fused into the first GEMM's
epilogue, the second GEMM consuming it in-register).  On TPU, XLA's own
epilogue fusion covers the *elementwise* half (bias+GELU fuse into the
MXU matmul's output — pinned by ``tests/test_on_chip.py::
TestXlaFusionClaim``) but still materializes the ``(tokens, ffn_hidden)``
activation between the two GEMMs in HBM twice per direction.  This
kernel closes that gap the same way ``ops/flash_attention.py`` does for
attention:

* forward: grid ``(m_blocks, f_blocks)`` with the ffn-hidden axis
  innermost; each step computes one ``(block_m, block_f)`` tile of
  ``z = x @ W1^T + b1`` (f32 accumulation on the MXU), applies the tanh
  GELU, and accumulates ``gelu(z) @ W2^T`` into a ``(block_m, n)`` f32
  VMEM scratch — the second GEMM consumes the activation tile while it
  is still in VMEM, so the full ``(m, f)`` activation never round-trips
  through HBM inside one grid row.  The pre-activation ``z`` is written
  out as the backward's residual (the flash-attention recompute trade:
  save the small thing, recompute the nonlinearity).
* backward: two kernels with the same blocking, both recomputing the
  GELU terms from the saved pre-activation — one accumulating ``dx``
  (f innermost), one walking ``(f_blocks, m_blocks)`` to accumulate
  ``dW1``/``db1``/``dW2`` in f32 scratch (m innermost).  ``db2`` is a
  plain row-sum of the output cotangent (one XLA reduce on an input —
  nothing to fuse).

Numerics: both GEMMs accumulate in f32 via ``preferred_element_type``
with operands kept in the activation dtype (full MXU bf16 rate); the
GELU and its hand-written tanh derivative run in f32.  Off-TPU the
public API dispatches to :func:`fused_ffn_reference`, which replays the
EXACT op order of the unfused ``ColumnParallelLinear -> gelu ->
RowParallelLinear`` path — so flipping the ``fused_ffn`` model knob is
bitwise-neutral on CPU f32, and the unit suite compares the kernel
(interpret mode) against the reference at the flash-attention
tolerances.

Padding parity: every extent is zero-padded to its block/lane multiple
inside the op and sliced back; zero rows/lanes are exact no-ops through
both GEMMs and the backward (``gelu(0) = 0`` kills the padded ffn
columns in the forward, zero cotangent rows kill them in the backward).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.multi_tensor_apply.bucketing import _round_up
from apex_tpu.utils.platform import (interpret_mode, tpu_compiler_params,
                                     use_pallas)

_f32 = jnp.float32

__all__ = ["fused_ffn", "fused_ffn_reference", "fused_ffn_tp"]


def _sds(shape, dtype, like):
    """vma-aware pallas output ShapeDtypeStruct (see
    :func:`apex_tpu.utils.collectives.sds_like`)."""
    from apex_tpu.utils.collectives import sds_like

    return sds_like(shape, dtype, like)


# ---------------------------------------------------------------------------
# tanh-GELU and its derivative (f32, shared by all kernels)
# ---------------------------------------------------------------------------

_GELU_C = 0.7978845608028654   # sqrt(2/pi)
_GELU_A = 0.044715


def _gelu(z):
    """tanh-approximate GELU on an f32 tile (same closed form
    ``jax.nn.gelu(z, approximate=True)`` lowers to)."""
    return jax.nn.gelu(z, approximate=True)


def _gelu_grad(z):
    """d/dz of the tanh GELU, in closed form so the backward recomputes
    it from the saved pre-activation instead of storing it."""
    z2 = z * z
    t = jnp.tanh(_GELU_C * z * (1.0 + _GELU_A * z2))
    return (0.5 * (1.0 + t)
            + 0.5 * z * (1.0 - t * t) * _GELU_C * (1.0 + 3.0 * _GELU_A * z2))


def _dot_t(a, b):
    """``a @ b^T`` contracting the trailing dims, f32 accumulation."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=_f32)


def _dot_colsum(a, b):
    """``a^T @ b`` contracting the leading dims, f32 accumulation."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=_f32)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _ffn_fwd_kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, y_ref, z1_ref,
                    acc_scr):
    fi = pl.program_id(1)
    nf = pl.num_programs(1)

    @pl.when(fi == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    x = x_ref[:]
    # z tile: (block_m, block_f) pre-activation, f32 accumulation
    z = _dot_t(x, w1_ref[:].astype(x.dtype)) + b1_ref[:].astype(_f32)
    z1_ref[:] = z.astype(z1_ref.dtype)
    h = _gelu(z).astype(x.dtype)
    # second GEMM consumes the activation tile straight from registers/
    # VMEM: acc += gelu(z) @ W2_block^T  ->  (block_m, n_pad)
    acc_scr[:] += _dot_t(h, w2_ref[:].astype(x.dtype))

    @pl.when(fi == nf - 1)
    def _finish():
        y_ref[:] = (acc_scr[:] + b2_ref[:].astype(_f32)).astype(y_ref.dtype)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _ffn_dx_kernel(dy_ref, z1_ref, w1_ref, w2_ref, dx_ref, dx_scr):
    fi = pl.program_id(1)
    nf = pl.num_programs(1)

    @pl.when(fi == 0)
    def _init():
        dx_scr[:] = jnp.zeros_like(dx_scr[:])

    dy = dy_ref[:]
    z = z1_ref[:].astype(_f32)
    # dh = dy @ W2_block: (block_m, n_pad) x (n_pad, block_f)
    dh = jax.lax.dot_general(dy, w2_ref[:].astype(dy.dtype),
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=_f32)
    dz = (dh * _gelu_grad(z)).astype(dy.dtype)
    # dx += dz @ W1_block: (block_m, block_f) x (block_f, k_pad)
    dx_scr[:] += jax.lax.dot_general(dz, w1_ref[:].astype(dy.dtype),
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=_f32)

    @pl.when(fi == nf - 1)
    def _finish():
        dx_ref[:] = dx_scr[:].astype(dx_ref.dtype)


def _ffn_dw_kernel(x_ref, dy_ref, z1_ref, w2_ref, dw1_ref, db1_ref,
                   dw2_ref, dw1_scr, db1_scr, dw2_scr):
    mi = pl.program_id(1)
    nm = pl.num_programs(1)

    @pl.when(mi == 0)
    def _init():
        dw1_scr[:] = jnp.zeros_like(dw1_scr[:])
        db1_scr[:] = jnp.zeros_like(db1_scr[:])
        dw2_scr[:] = jnp.zeros_like(dw2_scr[:])

    x = x_ref[:]
    dy = dy_ref[:]
    z = z1_ref[:].astype(_f32)
    h = _gelu(z)
    dh = jax.lax.dot_general(dy, w2_ref[:].astype(dy.dtype),
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=_f32)
    dz = dh * _gelu_grad(z)
    dzc = dz.astype(x.dtype)
    # dW1 += dz^T @ x: (block_f, block_m) x (block_m, k_pad)
    dw1_scr[:] += _dot_colsum(dzc, x)
    # dW2 += dy^T @ gelu(z): (n_pad, block_m) x (block_m, block_f)
    dw2_scr[:] += _dot_colsum(dy, h.astype(dy.dtype))
    # db1 += column-sum of dz as an MXU reduction to a (block_f, 1)
    # column (broadcast over the scratch's 128 lanes; lane 0 is read
    # back at the end — the flash lse unit-lane layout)
    ones = jnp.ones((dz.shape[0], 1), _f32)
    db1_scr[:] += _dot_colsum(dz, ones)

    @pl.when(mi == nm - 1)
    def _finish():
        dw1_ref[:] = dw1_scr[:].astype(dw1_ref.dtype)
        db1_ref[:] = db1_scr[:, 0:1]
        dw2_ref[:] = dw2_scr[:].astype(dw2_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _pad2(a, r, c):
    if a.shape != (r, c):
        a = jnp.pad(a, ((0, r - a.shape[0]), (0, c - a.shape[1])))
    return a


def _vmem(block, index_map):
    return pl.BlockSpec(block, index_map, memory_space=pltpu.VMEM)


def _ffn_fwd_impl(x, w1, b1, w2, b2, block_m, block_f):
    """All operands pre-padded 2D: x (m_p, k_p), w1 (f_p, k_p),
    b1 (1, f_p), w2 (n_p, f_p), b2 (1, n_p); returns padded (y, z1)."""
    m_p, k_p = x.shape
    f_p = w1.shape[0]
    n_p = w2.shape[0]
    nm, nf = m_p // block_m, f_p // block_f
    return pl.pallas_call(
        _ffn_fwd_kernel,
        grid=(nm, nf),
        in_specs=[_vmem((block_m, k_p), lambda mi, fi: (mi, 0)),
                  _vmem((block_f, k_p), lambda mi, fi: (fi, 0)),
                  _vmem((1, block_f), lambda mi, fi: (0, fi)),
                  _vmem((n_p, block_f), lambda mi, fi: (0, fi)),
                  _vmem((1, n_p), lambda mi, fi: (0, 0))],
        out_specs=[_vmem((block_m, n_p), lambda mi, fi: (mi, 0)),
                   _vmem((block_m, block_f), lambda mi, fi: (mi, fi))],
        out_shape=[_sds((m_p, n_p), x.dtype, x),
                   _sds((m_p, f_p), x.dtype, x)],
        scratch_shapes=[pltpu.VMEM((block_m, n_p), _f32)],
        compiler_params=tpu_compiler_params(("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(x, w1, b1, w2, b2)


def _ffn_bwd_impl(x, w1, w2, z1, dy, block_m, block_f):
    """Padded operands; returns padded (dx, dw1, db1, dw2) with db1 as
    an (f_p, 1) f32 column."""
    m_p, k_p = x.shape
    f_p = w1.shape[0]
    n_p = w2.shape[0]
    nm, nf = m_p // block_m, f_p // block_f
    dx = pl.pallas_call(
        _ffn_dx_kernel,
        grid=(nm, nf),
        in_specs=[_vmem((block_m, n_p), lambda mi, fi: (mi, 0)),
                  _vmem((block_m, block_f), lambda mi, fi: (mi, fi)),
                  _vmem((block_f, k_p), lambda mi, fi: (fi, 0)),
                  _vmem((n_p, block_f), lambda mi, fi: (0, fi))],
        out_specs=_vmem((block_m, k_p), lambda mi, fi: (mi, 0)),
        out_shape=_sds((m_p, k_p), x.dtype, x),
        scratch_shapes=[pltpu.VMEM((block_m, k_p), _f32)],
        compiler_params=tpu_compiler_params(("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(dy, z1, w1, w2)

    # weight grads: swap the walk — f blocks outer (parallel), m inner
    dw1, db1, dw2 = pl.pallas_call(
        _ffn_dw_kernel,
        grid=(nf, nm),
        in_specs=[_vmem((block_m, k_p), lambda fi, mi: (mi, 0)),
                  _vmem((block_m, n_p), lambda fi, mi: (mi, 0)),
                  _vmem((block_m, block_f), lambda fi, mi: (mi, fi)),
                  _vmem((n_p, block_f), lambda fi, mi: (0, fi))],
        out_specs=[_vmem((block_f, k_p), lambda fi, mi: (fi, 0)),
                   _vmem((block_f, 1), lambda fi, mi: (fi, 0)),
                   _vmem((n_p, block_f), lambda fi, mi: (0, fi))],
        out_shape=[_sds((f_p, k_p), w1.dtype, w1),
                   _sds((f_p, 1), _f32, w1),
                   _sds((n_p, f_p), w2.dtype, w2)],
        scratch_shapes=[pltpu.VMEM((block_f, k_p), _f32),
                        pltpu.VMEM((block_f, 128), _f32),
                        pltpu.VMEM((n_p, block_f), _f32)],
        compiler_params=tpu_compiler_params(("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(x, dy, z1, w2)
    return dx, dw1, db1, dw2


# ---------------------------------------------------------------------------
# custom-VJP wrapper over (m, k) 2D operands
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _ffn(x, w1, b1, w2, b2, block_m, block_f):
    y, _ = _ffn_vjp_fwd(x, w1, b1, w2, b2, block_m, block_f)
    return y


def _ffn_vjp_fwd(x, w1, b1, w2, b2, block_m, block_f):
    m, k = x.shape
    f = w1.shape[0]
    n = w2.shape[0]
    m_p, k_p = _round_up(m, block_m), _round_up(k, 128)
    f_p, n_p = _round_up(f, block_f), _round_up(n, 128)
    xp = _pad2(x, m_p, k_p)
    w1p = _pad2(w1, f_p, k_p)
    w2p = _pad2(w2, n_p, f_p)
    yp, z1p = _ffn_fwd_impl(xp, w1p, _pad2(b1[None, :], 1, f_p), w2p,
                            _pad2(b2[None, :], 1, n_p), block_m, block_f)
    # residuals: inputs + the saved pre-activation (activation dtype);
    # the GELU terms are recomputed from z1 in both backward kernels
    return yp[:m, :n], (x, w1, b1, w2, b2, z1p)


def _ffn_vjp_bwd(block_m, block_f, res, dy):
    x, w1, b1, w2, b2, z1p = res
    m, k = x.shape
    f = w1.shape[0]
    n = w2.shape[0]
    m_p, f_p = z1p.shape
    k_p = _round_up(k, 128)
    n_p = _round_up(n, 128)
    dyp = _pad2(dy, m_p, n_p)
    dx, dw1, db1, dw2 = _ffn_bwd_impl(
        _pad2(x, m_p, k_p), _pad2(w1, f_p, k_p), _pad2(w2, n_p, f_p),
        z1p, dyp, block_m, block_f)
    db2 = jnp.sum(dy.astype(_f32), axis=0)
    return (dx[:m, :k],
            dw1[:f, :k],
            db1[:f, 0].astype(b1.dtype),
            dw2[:n, :f],
            db2.astype(b2.dtype))


_ffn.defvjp(_ffn_vjp_fwd, _ffn_vjp_bwd)


# ---------------------------------------------------------------------------
# reference + public API
# ---------------------------------------------------------------------------

def fused_ffn_reference(x, w1, b1, w2, b2=None):
    """Unfused reference: the EXACT op order of the model FFN path
    (``ColumnParallelLinear`` GEMM+bias -> tanh GELU ->
    ``RowParallelLinear`` GEMM [+ bias]) — so the off-TPU fallback is
    bitwise-identical to running the unfused layers."""
    h = x @ w1.astype(x.dtype).T
    h = h + b1.astype(h.dtype)
    h = jax.nn.gelu(h, approximate=True)
    y = h @ w2.astype(h.dtype).T
    if b2 is not None:
        y = y + b2.astype(y.dtype)
    return y


def _fit(requested, extent):
    """Largest candidate block <= requested dividing the lane-padded
    extent (the flash-attention block picker)."""
    padded = _round_up(extent, 128)
    for cand in (requested, 512, 384, 256, 128):
        if cand <= requested and padded % cand == 0:
            return cand
    return min(requested, padded)


def fused_ffn(x, w1, b1, w2, b2=None, *, block_m=256, block_f=512):
    """Fused ``gelu(x @ w1^T + b1) @ w2^T [+ b2]`` over ``(..., k)``.

    ``w1`` is ``(ffn_hidden, k)`` and ``w2`` ``(out, ffn_hidden)`` —
    the ``(out_features, in_features)`` layout of the TP linear layers,
    so a column-sharded ``w1`` / row-sharded ``w2`` pair drops in
    per-rank unchanged.  ``b2=None`` skips the second bias (the
    RowParallel case, where the bias is added *after* the cross-rank
    reduce).  Forward saves only the ``(m, ffn_hidden)`` pre-activation
    (activation dtype) for the backward; both GEMMs accumulate f32.

    Off-TPU (``use_pallas() == False``) this dispatches to
    :func:`fused_ffn_reference`, which replays the unfused op order
    bitwise.
    """
    if x.shape[-1] != w1.shape[1]:
        raise ValueError(f"x features {x.shape[-1]} != w1 in-dim "
                         f"{w1.shape[1]}")
    if b1.shape != (w1.shape[0],):
        raise ValueError(f"b1 shape {b1.shape} != ({w1.shape[0]},)")
    if w2.shape[1] != w1.shape[0]:
        raise ValueError(f"w2 in-dim {w2.shape[1]} != w1 out-dim "
                         f"{w1.shape[0]}")
    if b2 is not None and b2.shape != (w2.shape[0],):
        raise ValueError(f"b2 shape {b2.shape} != ({w2.shape[0]},)")
    if not use_pallas():
        return fused_ffn_reference(x, w1, b1, w2, b2)
    lead = x.shape[:-1]
    x2 = x.reshape((-1, x.shape[-1]))
    block_m = _fit(int(block_m), x2.shape[0])
    block_f = _fit(int(block_f), w1.shape[0])
    b2_arr = b2 if b2 is not None else jnp.zeros((w2.shape[0],), w2.dtype)
    y = _ffn(x2, w1, b1, w2, b2_arr, block_m, block_f)
    return y.reshape(lead + (w2.shape[0],))


def fused_ffn_tp(x, w1, b1, w2, b2, *, tensor_parallel_size=1,
                 axis_name=None, sequence_parallel=False, seq_dim=1):
    """The model-side fused FFN block: the kernel wrapped in the exact
    Megatron TP/SP edge collectives the unfused ``ColumnParallelLinear
    -> gelu -> RowParallelLinear`` pair uses.

    ``w1``/``b1`` are the column-sharded fc1 params (ffn dim over the
    tensor axis), ``w2`` the row-sharded fc2 weight, ``b2`` the
    UNsharded fc2 bias — added after the cross-rank reduce, wrapped in
    ``copy_to_tensor_model_parallel_region`` under SP so the replicated
    bias's cotangent is psummed over ranks (the RowParallelLinear
    ``_bias()`` discipline).  At ``overlap_chunks > 0`` the unfused
    path rings its collective+GEMM pairs; the fused kernel takes
    precedence for the FFN pair and uses the plain SP edges (the
    in-VMEM fusion replaces what the ring was hiding), so parity vs
    the ringed path is the SP epsilon bound, not bitwise.
    """
    if tensor_parallel_size <= 1:
        return fused_ffn(x, w1, b1, w2, b2)
    from apex_tpu.transformer import tensor_parallel as tp

    if sequence_parallel:
        x = tp.gather_from_sequence_parallel_region(x, axis_name, seq_dim)
    else:
        x = tp.copy_to_tensor_model_parallel_region(x, axis_name)
    y = fused_ffn(x, w1, b1, w2, None)
    if sequence_parallel:
        y = tp.reduce_scatter_to_sequence_parallel_region(y, axis_name,
                                                          seq_dim)
        b2 = tp.copy_to_tensor_model_parallel_region(b2, axis_name)
    else:
        y = tp.reduce_from_tensor_model_parallel_region(y, axis_name)
    return y + b2.astype(y.dtype)
