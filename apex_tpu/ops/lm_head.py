"""Fused LM-head cross entropy — blockwise (logit-free) linear + softmax
cross entropy as Pallas kernels.

The reference's ``apex/contrib/xentropy`` fuses softmax+CE to avoid
recomputing softmax in the backward; the logits themselves still
materialize (O(N·V)).  On TPU the LM head is memory-bound on exactly that
(b·s × vocab) logits round-trip — ~3.3 GB for GPT-350M at batch 16 — so
this op goes one step further and never forms logits at all (the
flash-attention trade applied to the classifier: blockwise online
logsumexp over vocab tiles, recompute probabilities in the backward from
the saved per-token logsumexp).  Beyond-reference; the contrib xentropy
surface is unchanged.

Math (per token i with target y): ``loss_i = lse_i − x_i·W_{y_i}`` where
``lse_i = logsumexp_v(x_i·W_v)``.  Backward with upstream cotangent g_i:
``dX_i = g_i (p_i − onehot(y_i)) W`` and ``dW = Σ_i g_i (p_i −
onehot(y_i))^T x_i`` with ``p_iv = exp(x_i·W_v − lse_i)`` recomputed per
tile.

Forward grid ``(token_blocks, vocab_blocks)`` (vocab innermost): running
row-max/row-sum scratch like the flash kernel, plus the target logit
captured by an in-tile one-hot select.  Backward runs two kernels with
transposed grids: dX accumulates over vocab blocks, dW over token blocks.

Off-TPU the same semantics run as a materialized jnp reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.multi_tensor_apply.bucketing import _round_up
from apex_tpu.utils.collectives import sds_like as _sds
from apex_tpu.utils.platform import (interpret_mode, tpu_compiler_params,
                                     use_pallas)

_f32 = jnp.float32
_MASK = -1e30

__all__ = ["fused_linear_cross_entropy",
           "fused_linear_cross_entropy_reference"]


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _dot_dtype(x_dtype, w_dtype):
    """Operand dtype for the logit dots: the bf16 fast path is taken only
    when BOTH operands are bf16 (accumulation stays f32 via
    ``preferred_element_type``) — under O2 the whole tied head IS bf16,
    and upcasting matched-bf16 operands to f32 costs MXU rate for
    accumulation precision the f32 path already provides.  A MIXED
    f32/bf16 pair upcasts to f32: downcasting the f32 side would silently
    drop operand precision in the loss and both gradient GEMMs for any
    caller passing f32 hidden states with a bf16 tied embedding (ADVICE
    round 5).  (Only bf16 is special: Mosaic has no f16 vector type, so
    f16 operands never reach these kernels.)"""
    if (jnp.dtype(x_dtype) == jnp.bfloat16
            and jnp.dtype(w_dtype) == jnp.bfloat16):
        return jnp.bfloat16
    return _f32


def _fwd_kernel(n_valid, v_valid, block_t, block_v,
                tgt_ref, x_ref, w_ref, loss_ref, lse_ref,
                m_scr, l_scr, t_scr):
    vi = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr[:], _MASK)
        l_scr[:] = jnp.zeros_like(l_scr[:])
        t_scr[:] = jnp.zeros_like(t_scr[:])

    dt = _dot_dtype(x_ref.dtype, w_ref.dtype)
    x = x_ref[:].astype(dt)
    w = w_ref[:].astype(dt)
    s = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=_f32)
    v_pos = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_t, block_v), 1)
    valid = v_pos < v_valid
    s = jnp.where(valid, s, _MASK)

    m_prev = m_scr[:, :1]
    m_cur = jnp.maximum(jnp.max(s, axis=1, keepdims=True), m_prev)
    alpha = jnp.exp(m_prev - m_cur)
    p = jnp.where(valid, jnp.exp(s - m_cur), 0.0)
    l_scr[:] = jnp.broadcast_to(
        alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True),
        l_scr.shape)
    m_scr[:] = jnp.broadcast_to(m_cur, m_scr.shape)
    # capture the target logit when this tile owns the row's target
    hit = v_pos == tgt_ref[:]          # (block_t, 1) broadcasts over cols
    t_scr[:] = t_scr[:] + jnp.broadcast_to(
        jnp.sum(jnp.where(hit, s, 0.0), axis=1, keepdims=True),
        t_scr.shape)

    @pl.when(vi == nv - 1)
    def _finish():
        m = m_scr[:, :1]
        l = jnp.where(l_scr[:, :1] == 0.0, 1.0, l_scr[:, :1])
        lse = m + jnp.log(l)
        lse_ref[:] = lse
        loss_ref[:] = lse - t_scr[:, :1]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _p_minus_onehot(s_valid, vi, block_t, block_v, v_valid, tgt, lse, s):
    """g-free ``p − onehot(target)`` for one tile, invalid columns zero."""
    v_pos = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_t, block_v), 1)
    valid = v_pos < v_valid
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    hit = v_pos == tgt                 # (block_t, 1) broadcasts over cols
    return p - jnp.where(hit, 1.0, 0.0)


def _dx_kernel(v_valid, block_t, block_v,
               tgt_ref, x_ref, w_ref, lse_ref, g_ref, dx_ref, dx_scr):
    vi = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        dx_scr[:] = jnp.zeros_like(dx_scr[:])

    dt = _dot_dtype(x_ref.dtype, w_ref.dtype)
    x = x_ref[:].astype(dt)
    w = w_ref[:].astype(dt)
    s = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=_f32)
    ds = _p_minus_onehot(None, vi, x.shape[0], block_v, v_valid,
                         tgt_ref[:], lse_ref[:], s)
    ds = ds * g_ref[:]                       # per-token upstream cotangent
    # dS cast to the operand dtype for the MXU-rate dot (same trade as
    # the flash backward: dS is written back at input precision)
    dx_scr[:] += jax.lax.dot_general(ds.astype(dt), w,
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=_f32)

    @pl.when(vi == nv - 1)
    def _finish():
        dx_ref[:] = dx_scr[:].astype(dx_ref.dtype)


def _dw_kernel(n_valid, v_valid, block_t, block_v,
               tgt_ref, x_ref, w_ref, lse_ref, g_ref, dw_ref, dw_scr):
    vi = pl.program_id(0)
    ti = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(ti == 0)
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr[:])

    dt = _dot_dtype(x_ref.dtype, w_ref.dtype)
    x = x_ref[:].astype(dt)
    w = w_ref[:].astype(dt)
    s = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=_f32)
    block_t_ = x.shape[0]
    ds = _p_minus_onehot(None, vi, block_t_, block_v, v_valid,
                         tgt_ref[:], lse_ref[:], s)
    ds = ds * g_ref[:]
    # zero padded token rows: their lse is garbage
    t_pos = ti * block_t + jax.lax.broadcasted_iota(
        jnp.int32, (block_t_, block_v), 0)
    ds = jnp.where(t_pos < n_valid, ds, 0.0)
    dw_scr[:] += jax.lax.dot_general(ds.astype(dt), x,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=_f32)

    @pl.when(ti == nt - 1)
    def _finish():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing + custom VJP
# ---------------------------------------------------------------------------

def _pad2(x, rows, cols):
    r, c = x.shape
    if r != rows or c != cols:
        x = jnp.pad(x, ((0, rows - r), (0, cols - c)))
    return x


def _compiler_params():
    return tpu_compiler_params(("parallel", "arbitrary"))


def _fwd_impl(x, w, targets, block_t, block_v):
    N, H = x.shape
    V = w.shape[0]
    Np, Vp = _round_up(N, block_t), _round_up(V, block_v)
    Hp = _round_up(H, 128)
    xp = _pad2(x, Np, Hp)
    wp = _pad2(w, Vp, Hp)
    # padded token rows target -1: never matches a vocab position;
    # column layout — Mosaic rejects 1-D int operands whose XLA tiling
    # disagrees with the block shape
    tp = jnp.pad(targets.astype(jnp.int32), (0, Np - N),
                 constant_values=-1).reshape(Np, 1)
    kernel = functools.partial(_fwd_kernel, N, V, block_t, block_v)
    loss, lse = pl.pallas_call(
        kernel,
        grid=(Np // block_t, Vp // block_v),
        in_specs=[
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, Hp), lambda t, v: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_v, Hp), lambda t, v: (v, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[_sds((Np, 1), _f32, xp),
                   _sds((Np, 1), _f32, xp)],
        scratch_shapes=[pltpu.VMEM((block_t, 128), _f32),
                        pltpu.VMEM((block_t, 128), _f32),
                        pltpu.VMEM((block_t, 128), _f32)],
        compiler_params=_compiler_params(),
        interpret=interpret_mode(),
    )(tp, xp, wp)
    return loss[:N, 0], lse


def _bwd_impl(x, w, targets, lse, g, block_t, block_v):
    N, H = x.shape
    V = w.shape[0]
    Np, Vp = _round_up(N, block_t), _round_up(V, block_v)
    Hp = _round_up(H, 128)
    xp = _pad2(x, Np, Hp)
    wp = _pad2(w, Vp, Hp)
    tp = jnp.pad(targets.astype(jnp.int32), (0, Np - N),
                 constant_values=-1).reshape(Np, 1)
    gp = jnp.pad(g.astype(_f32).reshape(N, 1), ((0, Np - N), (0, 0)))

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, V, block_t, block_v),
        grid=(Np // block_t, Vp // block_v),
        in_specs=[
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, Hp), lambda t, v: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_v, Hp), lambda t, v: (v, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_t, Hp), lambda t, v: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_sds((Np, Hp), x.dtype, xp),
        scratch_shapes=[pltpu.VMEM((block_t, Hp), _f32)],
        compiler_params=_compiler_params(),
        interpret=interpret_mode(),
    )(tp, xp, wp, lse, gp)

    dw = pl.pallas_call(
        functools.partial(_dw_kernel, N, V, block_t, block_v),
        grid=(Vp // block_v, Np // block_t),
        in_specs=[
            pl.BlockSpec((block_t, 1), lambda v, t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, Hp), lambda v, t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_v, Hp), lambda v, t: (v, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, 1), lambda v, t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_t, 1), lambda v, t: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_v, Hp), lambda v, t: (v, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_sds((Vp, Hp), w.dtype, xp),
        scratch_shapes=[pltpu.VMEM((block_v, Hp), _f32)],
        compiler_params=_compiler_params(),
        interpret=interpret_mode(),
    )(tp, xp, wp, lse, gp)
    return dx[:N, :H], dw[:V, :H]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused(x, w, targets, block_t, block_v):
    loss, _ = _fwd_impl(x, w, targets, block_t, block_v)
    return loss


def _fused_fwd(x, w, targets, block_t, block_v):
    loss, lse = _fwd_impl(x, w, targets, block_t, block_v)
    return loss, (x, w, targets, lse)


def _fused_bwd(block_t, block_v, res, g):
    x, w, targets, lse = res
    dx, dw = _bwd_impl(x, w, targets, lse, g, block_t, block_v)
    return dx.astype(x.dtype), dw.astype(w.dtype), None


_fused.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# public API + reference
# ---------------------------------------------------------------------------

def fused_linear_cross_entropy_reference(x, w, targets):
    """Materialized reference: ``-log softmax(x @ w.T)[targets]``."""
    logits = (x.astype(_f32) @ w.astype(_f32).T)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(
        logp, targets.reshape(-1, 1).astype(jnp.int32), axis=1)[:, 0]


def fused_linear_cross_entropy(x, w, targets, *, block_t=256,
                               block_v=512):
    """Per-token CE of the tied LM head WITHOUT materializing logits.

    ``x``: ``(N, H)`` hidden states; ``w``: ``(V, H)`` (tied embedding);
    ``targets``: ``(N,)`` int.  Returns per-token loss ``(N,)`` f32,
    differentiable in ``x`` and ``w``.  O(N·H + V·H) memory instead of
    O(N·V); fwd + both backward GEMMs run on vocab tiles in VMEM.
    """
    N, H = x.shape
    V = w.shape[0]
    if not use_pallas() or jnp.float16 in (x.dtype, w.dtype):
        # f16: Mosaic has no f16 vector type (same gate as
        # ops/multi_tensor.py::_use_kernel)
        return fused_linear_cross_entropy_reference(x, w, targets)
    return _fused(x, w, targets, int(block_t), int(block_v))
