"""Flash attention — TPU rebuild of the reference's fused-attention tier
(``apex/contrib/fmha/`` fixed-seqlen fused MHA and
``apex/contrib/multihead_attn/`` fused self/encdec attention kernels).

The CUDA kernels tile QK^T into SRAM and fuse scale+mask+softmax+PV per
tile; the TPU equivalent is the blockwise online-softmax (flash) algorithm
as Pallas kernels:

* forward: grid ``(batch*heads, q_blocks, k_blocks)`` with the k axis
  innermost; running row-max ``m``, row-sum ``l`` and the output
  accumulator live in VMEM scratch across the k iterations, so the
  ``(s, s)`` score matrix is never materialized in HBM.  Saves the
  per-row logsumexp for the backward.
* backward: two passes with the same blocking — one accumulating ``dq``
  (k innermost), one accumulating ``dk``/``dv`` (q innermost) — each
  recomputing ``p = exp(q k^T * scale - lse)`` from the saved logsumexp
  instead of storing probabilities (the flash-attention recompute trade).

Unlike the reference's fmha (seqlen <= 512 templates) there is no sequence
cap; unlike the pre-flash ``multihead_attn`` kernels the memory is O(s)
not O(s^2).  Padding parity: the reference packs variable-length batches
via ``cu_seqlens``; here batches are dense ``(b, h, s, d)`` with an
optional per-batch ``kv_seqlens`` — key positions >= the row's length are
masked out, matching the packed semantics on padded inputs.  Probability
dropout is fused into all three kernels via a counter-hash keep mask
(see the "fused probability dropout" section below), the reference's
philox-fused design without the O(s^2) mask storage.

Off-TPU the same semantics run as a materialized jnp reference (the unit
suite compares the two; on TPU the Pallas path is the default).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.multi_tensor_apply.bucketing import _round_up
from apex_tpu.utils.platform import (interpret_mode, tpu_compiler_params,
                                     use_pallas)

_f32 = jnp.float32
_MASK = -1e30  # finite "minus infinity": exp(_MASK - m) == 0, no NaNs

__all__ = ["flash_attention", "flash_attention_reference",
           "flash_attention_decode", "flash_attention_decode_reference",
           "flash_attention_decode_paged", "flash_attention_chunk_paged",
           "gather_paged_kv"]


# ---------------------------------------------------------------------------
# fused probability dropout
# ---------------------------------------------------------------------------
#
# The reference fuses philox-counter dropout into the probability tile
# (apex/contrib/csrc/multihead_attn/dropout.cuh, philox.h): the mask is a
# pure function of (seed, position), so forward and backward regenerate it
# instead of storing an O(s^2) mask.  Same design here, with a
# lowbias32-style integer hash instead of philox: pure jnp/lax integer
# math, so the SAME function runs inside the Pallas kernels (compiled or
# interpret mode) and in the dense jnp fallback — the mask is bit-identical
# across all paths and invariant to the kernel's block-size choice.
#
# Dropout semantics: inverted dropout on the NORMALIZED probabilities —
# the softmax denominator ``l`` accumulates the undropped ``p`` (the saved
# logsumexp is dropout-free), and the keep/(1-rate) factor applies only to
# the PV matmul.  Backward: with D the keep-scale matrix and P the
# undropped probabilities, ``o = (P∘D)V`` gives ``dV = (P∘D)^T dO``,
# ``dS = P∘(D∘(dO V^T) - delta)`` where ``delta = rowsum(dO∘O)`` — the
# delta trick survives dropout unchanged because
# ``rowsum(dO∘O) = rowsum(P∘D∘(dO V^T))``.


def _mix32(x):
    """lowbias32 avalanche mix (public-domain integer hash)."""
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def _dropout_hash(seed, bh, q_pos, k_pos):
    """uint32 hash of (seed, batch*head index, q position, k position).

    ``seed``/``bh`` are scalars, ``q_pos``/``k_pos`` integer arrays that
    broadcast against each other; chained mixing (not a packed linear
    counter) so large sequence extents cannot alias by overflow.
    """
    h = _mix32(jnp.asarray(bh).astype(jnp.uint32)
               ^ _mix32(jnp.asarray(seed).astype(jnp.uint32)))
    h = _mix32(h ^ q_pos.astype(jnp.uint32))
    return _mix32(h ^ k_pos.astype(jnp.uint32))


def _keep_threshold(rate):
    """Static uint32 threshold with P(hash >= threshold) = 1 - rate."""
    return jnp.uint32(min(max(int(round(rate * 2.0 ** 32)), 0),
                          2 ** 32 - 1))


def _keep_scale_tile(seed, bh, qi, ki, block_q, block_k, rate):
    """(block_q, block_k) f32 tile of keep/(1-rate) factors ("D")."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    h = _dropout_hash(seed, bh, q_pos, k_pos)
    return jnp.where(h >= _keep_threshold(rate),
                     jnp.float32(1.0 / (1.0 - rate)), 0.0)


def dropout_keep_scale(seed, n_bh, sq, sk, rate):
    """Dense ``(n_bh, sq, sk)`` keep-scale matrix — the SAME hash the
    fused kernels regenerate per tile, materialized (for the jnp
    fallback and for parity tests against the fused path)."""
    bh = jnp.arange(n_bh, dtype=jnp.int32)[:, None, None]
    q_pos = jnp.arange(sq, dtype=jnp.int32)[None, :, None]
    k_pos = jnp.arange(sk, dtype=jnp.int32)[None, None, :]
    h = _dropout_hash(seed, bh, q_pos, k_pos)
    return jnp.where(h >= _keep_threshold(rate),
                     jnp.float32(1.0 / (1.0 - rate)), 0.0)


def _sds(shape, dtype, like):
    """vma-aware pallas output ShapeDtypeStruct (see
    :func:`apex_tpu.utils.collectives.sds_like`)."""
    from apex_tpu.utils.collectives import sds_like

    return sds_like(shape, dtype, like)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(causal, scale, rate, sq, block_q, block_k, masked,
                len_ref, seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr[:], _MASK)
        l_scr[:] = jnp.zeros_like(l_scr[:])
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    def compute():
        # operands stay in their native dtype (bf16 rides the MXU at
        # full rate; upcasting first would run the dot at f32 rate,
        # ~1/8 on v5e) — accumulation is f32 via preferred_element_type
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=_f32) * scale
        if masked:
            # ``masked`` is static: dense full-length non-causal calls
            # (the BERT shape) skip the iota/compare/select passes
            # (same-window A/B on v5e measures this neutral-to-slightly
            # -positive — Mosaic overlaps the VPU mask work with the
            # dots — kept because it is free specialization, mirroring
            # the reference fmha's seqlen-templated kernels)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = k_pos < len_ref[b]
            if causal:
                valid = valid & (k_pos <= q_pos)
            s = jnp.where(valid, s, _MASK)

        m_prev = m_scr[:, :1]
        m_cur = jnp.maximum(jnp.max(s, axis=1, keepdims=True), m_prev)
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        if masked:
            p = jnp.where(valid, p, 0.0)
        # l accumulates the UNDROPPED p (softmax normalizes pre-dropout);
        # the keep/(1-rate) factor touches only the PV matmul
        l_cur = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        if rate > 0.0:
            p = p * _keep_scale_tile(seed_ref[0], b, qi, ki, block_q,
                                     block_k, rate)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=_f32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_cur, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_cur, l_scr.shape)

    if causal:
        # blocks strictly above the diagonal contribute nothing
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finish():
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m + jnp.log(l_safe)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _recompute_p(causal, scale, qi, ki, block_q, block_k, masked, kv_len,
                 q, k, lse):
    """p = exp(q k^T * scale - lse) with the forward's mask re-applied.
    ``q``/``k`` native dtype; accumulation f32 (MXU-rate dots).
    ``masked`` static False (dense full-length non-causal) skips the
    mask recompute, matching the forward."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=_f32) * scale
    if not masked:
        return jnp.exp(s - lse), None
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = k_pos < kv_len
    if causal:
        valid = valid & (k_pos <= q_pos)
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    return p, valid


def _dq_kernel(causal, scale, rate, sq, block_q, block_k, masked,
               len_ref, seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, dq_scr):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr[:])

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                      # (block_q, 1)
        p, _ = _recompute_p(causal, scale, qi, ki, block_q, block_k,
                            masked, len_ref[b], q, k, lse)
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=_f32)
        if rate > 0.0:
            # dP = D∘(dO V^T): regenerate the forward's mask for this tile
            dp = dp * _keep_scale_tile(seed_ref[0], b, qi, ki, block_q,
                                       block_k, rate)
        ds = p * (dp - delta_ref[0]) * scale
        # ds cast to the operand dtype for the MXU-rate dot (the flash
        # CUDA kernels do the same: dS is written back at input precision)
        dq_scr[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=_f32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(causal, scale, rate, sq, block_q, block_k, masked,
                len_ref, seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_scr, dv_scr):
    b = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr[:])
        dv_scr[:] = jnp.zeros_like(dv_scr[:])

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                      # (block_q, 1)
        p, valid = _recompute_p(causal, scale, qi, ki, block_q, block_k,
                                masked, len_ref[b], q, k, lse)
        if masked:
            # zero padded q rows: their lse/delta are garbage and
            # p.T @ do would poison every dk/dv row (forward never
            # reads them — it slices; the backward reduces over them).
            # ``masked`` is True whenever the q extent is padded.
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            p = jnp.where(q_pos < sq, p, 0.0)
        if rate > 0.0:
            # same (seed, b, qi, ki) stream as the forward — note this
            # kernel's grid is (B, k, q), so the logical (qi, ki) pair is
            # (program_id(2), program_id(1))
            dmask = _keep_scale_tile(seed_ref[0], b, qi, ki, block_q,
                                     block_k, rate)
            pd = p * dmask
        else:
            pd = p
        dv_scr[:] += jax.lax.dot_general(pd.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=_f32)
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=_f32)
        if rate > 0.0:
            dp = dp * dmask
        ds = p * (dp - delta_ref[0]) * scale
        dk_scr[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=_f32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _pad_qkv(x, s_pad, d_pad):
    b, s, d = x.shape
    if s != s_pad or d != d_pad:
        x = jnp.pad(x, ((0, 0), (0, s_pad - s), (0, d_pad - d)))
    return x


def _specs(block_q, block_k, d_pad, which):
    """BlockSpecs for grid (B, i, j); ``which`` selects the role."""
    if which == "len":
        # whole (B,) vector resident in SMEM; kernels index program_id(0)
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    if which == "outer":        # follows grid dim 1 (rows of the output)
        return pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    if which == "inner":        # follows grid dim 2 (reduced-over axis)
        return pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0),
                            memory_space=pltpu.VMEM)
    if which == "outer_vec":    # (B, s, 1) per-row stats following dim 1
        # (block_q, 1) trailing dims: sublane divisible by 8, unit lane
        # matching the array — the TPU-legal layout for row statistics
        return pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    if which == "inner_vec":
        return pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0),
                            memory_space=pltpu.VMEM)
    raise ValueError(which)


def _compiler_params():
    return tpu_compiler_params(("parallel", "parallel", "arbitrary"))


def _flash_fwd_impl(q, k, v, kv_lens, seed, causal, scale, rate,
                    block_q, block_k, masked):
    """q,k,v: (B, s, d) padded inputs; returns (o, lse) padded."""
    B, sq, d_pad = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    kernel = functools.partial(_fwd_kernel, causal, scale, rate, sq,
                               block_q, block_k, masked)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, nq, nk),
        in_specs=[_specs(block_q, block_k, d_pad, "len"),
                  _specs(block_q, block_k, d_pad, "len"),
                  _specs(block_q, block_k, d_pad, "outer"),
                  _specs(block_q, block_k, d_pad, "inner"),
                  _specs(block_q, block_k, d_pad, "inner")],
        out_specs=[_specs(block_q, block_k, d_pad, "outer"),
                   _specs(block_q, block_k, d_pad, "outer_vec")],
        out_shape=[_sds((B, sq, d_pad), q.dtype, q),
                   _sds((B, sq, 1), _f32, q)],
        scratch_shapes=[pltpu.VMEM((block_q, 128), _f32),
                        pltpu.VMEM((block_q, 128), _f32),
                        pltpu.VMEM((block_q, d_pad), _f32)],
        compiler_params=_compiler_params(),
        interpret=interpret_mode(),
    )(kv_lens, seed, q, k, v)
    return o, lse


def _flash_bwd_impl(q, k, v, o, lse, do, kv_lens, seed, causal, scale,
                    rate, block_q, block_k, true_sq, masked):
    """``true_sq`` is the UNPADDED query length — the dkv kernel's
    padded-row guard must compare against it, not the padded extent."""
    B, sq, d_pad = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    delta = jnp.sum(do.astype(_f32) * o.astype(_f32), axis=-1,
                    keepdims=True)                              # (B, sq, 1)

    dq_kernel = functools.partial(_dq_kernel, causal, scale, rate, sq,
                                  block_q, block_k, masked)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, nq, nk),
        in_specs=[_specs(block_q, block_k, d_pad, "len"),
                  _specs(block_q, block_k, d_pad, "len"),
                  _specs(block_q, block_k, d_pad, "outer"),
                  _specs(block_q, block_k, d_pad, "inner"),
                  _specs(block_q, block_k, d_pad, "inner"),
                  _specs(block_q, block_k, d_pad, "outer"),
                  _specs(block_q, block_k, d_pad, "outer_vec"),
                  _specs(block_q, block_k, d_pad, "outer_vec")],
        out_specs=_specs(block_q, block_k, d_pad, "outer"),
        out_shape=_sds((B, sq, d_pad), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d_pad), _f32)],
        compiler_params=_compiler_params(),
        interpret=interpret_mode(),
    )(kv_lens, seed, q, k, v, do, lse, delta)

    # dk/dv: swap the roles — grid dim 1 walks k blocks, dim 2 walks q
    dkv_kernel = functools.partial(_dkv_kernel, causal, scale, rate,
                                   true_sq, block_q, block_k, masked)
    q_spec = pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, j, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    vec_spec = _specs(block_q, block_k, d_pad, "inner_vec")
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B, nk, nq),
        in_specs=[_specs(block_q, block_k, d_pad, "len"),
                  _specs(block_q, block_k, d_pad, "len"),
                  q_spec, k_spec, k_spec, q_spec, vec_spec, vec_spec],
        out_specs=[k_spec, k_spec],
        out_shape=[_sds((B, sk, d_pad), k.dtype, k),
                   _sds((B, sk, d_pad), v.dtype, v)],
        scratch_shapes=[pltpu.VMEM((block_k, d_pad), _f32),
                        pltpu.VMEM((block_k, d_pad), _f32)],
        compiler_params=_compiler_params(),
        interpret=interpret_mode(),
    )(kv_lens, seed, q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper over (b, h, s, d)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, kv_seqlens, seed, causal, scale, block_q, block_k,
           rate, masked):
    out, _ = _flash_vjp_fwd(q, k, v, kv_seqlens, seed, causal, scale,
                            block_q, block_k, rate, masked)
    return out


def _flatten(q, k, v, kv_seqlens, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    sq_p = _round_up(sq, block_q)
    sk_p = _round_up(sk, block_k)
    d_p = _round_up(d, 128)
    q3 = _pad_qkv(q.reshape(b * h, sq, d), sq_p, d_p)
    k3 = _pad_qkv(k.reshape(b * h, sk, d), sk_p, d_p)
    v3 = _pad_qkv(v.reshape(b * h, sk, d), sk_p, d_p)
    lens = jnp.repeat(kv_seqlens.astype(jnp.int32), h)     # (b*h,)
    return q3, k3, v3, lens


def _flash_vjp_fwd(q, k, v, kv_seqlens, seed, causal, scale, block_q,
                   block_k, rate, masked):
    b, h, sq, d = q.shape
    q3, k3, v3, lens = _flatten(q, k, v, kv_seqlens, block_q, block_k)
    o3, lse = _flash_fwd_impl(q3, k3, v3, lens, seed, causal, scale,
                              rate, block_q, block_k, masked)
    out = o3[:, :sq, :d].reshape(b, h, sq, d)
    return out, (q, k, v, kv_seqlens, seed, o3, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, rate, masked, res, g):
    q, k, v, kv_seqlens, seed, o3, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    q3, k3, v3, lens = _flatten(q, k, v, kv_seqlens, block_q, block_k)
    do3 = _pad_qkv(g.reshape(b * h, sq, d), q3.shape[1], q3.shape[2])
    dq3, dk3, dv3 = _flash_bwd_impl(q3, k3, v3, o3, lse, do3, lens, seed,
                                    causal, scale, rate, block_q, block_k,
                                    sq, masked)
    dq = dq3[:, :sq, :d].reshape(b, h, sq, d).astype(q.dtype)
    dk = dk3[:, :sk, :d].reshape(b, h, sk, d).astype(k.dtype)
    dv = dv3[:, :sk, :d].reshape(b, h, sk, d).astype(v.dtype)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# public API + jnp reference
# ---------------------------------------------------------------------------

def flash_attention_reference(q, k, v, causal=False, softmax_scale=None,
                              kv_seqlens=None, key_padding_mask=None,
                              dropout=0.0, dropout_rng=None,
                              dropout_mask=None):
    """Materialized-scores reference with identical masking semantics —
    the unfused baseline every fused op is tested against, and the
    single fallback for features the flash kernel cannot express
    (arbitrary ``key_padding_mask``; contrib ``multihead_attn``/``fmha``
    delegate here for those).

    ``key_padding_mask``: ``(b, sk)`` bool, True = masked out (apex
    convention).  A fully masked row yields a zero output, matching the
    kernel's ``l == 0`` guard.

    Dropout: ``dropout_mask`` is an explicit ``(b, h, sq, sk)``
    keep-scale matrix multiplied into the probabilities (how the fused
    kernel's hash mask is replayed for parity tests / the jnp fallback);
    ``dropout``+``dropout_rng`` is the ``jax.random`` variant.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(_f32),
                   k.astype(_f32)) * scale
    k_pos = jnp.arange(sk)
    valid = jnp.ones((b, 1, 1, sk), bool) if kv_seqlens is None else (
        k_pos[None, :] < kv_seqlens[:, None])[:, None, None, :]
    if key_padding_mask is not None:
        valid = valid & ~key_padding_mask[:, None, None, :]
    if causal:
        valid = valid & (k_pos[None, None, None, :]
                         <= jnp.arange(sq)[None, None, :, None])
    s = jnp.where(valid, s, _MASK)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(valid, p, 0.0)
    if dropout_mask is not None:
        p = p * dropout_mask.astype(p.dtype)
    elif dropout > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout > 0 needs dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# single-query decode path (KV-cache inference)
# ---------------------------------------------------------------------------
#
# Autoregressive decode attends ONE query token per sequence against the
# accumulated KV cache — there is no O(s^2) score matrix and no backward
# pass, but the full-sequence kernel would still pad the query extent to a
# whole q block and mask (block_q - 1) dead rows.  The decode kernel keeps
# the same online-softmax accumulation with a 1-row query tile, a grid of
# (batch, heads, k_blocks), and a dynamic per-row length bound from the
# cache occupancy, reading K/V directly in the cache layout
# ``(batch, max_seq, heads, head_dim)`` so no transpose of the cache ever
# materializes.  Blocks entirely past the row's length are skipped at
# runtime (the decode-side analogue of the causal block skip).  A
# production kernel would additionally tile multiple heads per program to
# fill the MXU sublanes; this one optimizes for sharing the flash
# forward's structure and numerics (f32 accumulation over a bf16 cache).


def _decode_kernel(scale, block_k, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr):
    b = pl.program_id(0)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr[:], _MASK)
        l_scr[:] = jnp.zeros_like(l_scr[:])
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    @pl.when(ki * block_k < len_ref[b])
    def _compute():
        q = q_ref[0]                              # (1, d_pad)
        k = k_ref[0, :, 0, :]                     # (block_k, d_pad)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=_f32) * scale
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        valid = k_pos < len_ref[b]
        s = jnp.where(valid, s, _MASK)
        m_prev = m_scr[:, :1]
        m_cur = jnp.maximum(jnp.max(s, axis=1, keepdims=True), m_prev)
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.where(valid, jnp.exp(s - m_cur), 0.0)
        l_cur = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, :, 0, :],
            (((1,), (0,)), ((), ())), preferred_element_type=_f32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_cur, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_cur, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def flash_attention_decode_reference(q, k_cache, v_cache, cache_lens,
                                     softmax_scale=None):
    """Materialized single-query reference over the cache layout — the
    off-TPU decode path and the parity baseline for the Pallas kernel.

    ``q``: ``(batch, heads, head_dim)`` (one token per sequence);
    ``k_cache``/``v_cache``: ``(batch, max_seq, heads, head_dim)``;
    ``cache_lens``: ``(batch,)`` valid lengths (the query's own position
    is ``cache_lens - 1``).  Scores and the PV reduction run in f32
    regardless of the cache dtype (bf16 cache, f32 accumulation).
    """
    b, S, h, d = k_cache.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    s = jnp.einsum("bhd,bshd->bhs", q.astype(_f32),
                   k_cache.astype(_f32)) * scale
    valid = (jnp.arange(S)[None, :]
             < cache_lens[:, None])[:, None, :]    # (b, 1, S)
    s = jnp.where(valid, s, _MASK)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(valid, p, 0.0)
    o = jnp.einsum("bhs,bshd->bhd", p, v_cache.astype(_f32))
    return o.astype(q.dtype)


def flash_attention_decode(q, k_cache, v_cache, cache_lens,
                           softmax_scale=None, block_k=512):
    """Single-token decode attention against a KV cache.

    ``q``: ``(batch, heads, head_dim)`` — the current token's query;
    ``k_cache``/``v_cache``: ``(batch, max_seq, heads, head_dim)`` — the
    preallocated cache INCLUDING the current token's K/V (write before
    attending); ``cache_lens``: ``(batch,)`` int, number of valid cache
    entries per row.  Entries at positions >= ``cache_lens`` are masked;
    causality is implied (every cached position <= the query's).

    Returns ``(batch, heads, head_dim)`` in ``q.dtype``; accumulation is
    f32 whatever the cache dtype.  On TPU a Pallas single-query kernel
    reads the cache layout directly; off-TPU the masked jnp reference
    runs (identical semantics, unit-tested against each other).
    """
    b, h, d = q.shape
    S = k_cache.shape[1]
    scale = float(softmax_scale if softmax_scale is not None
                  else d ** -0.5)
    cache_lens = cache_lens.astype(jnp.int32)
    if not use_pallas():
        return flash_attention_decode_reference(q, k_cache, v_cache,
                                                cache_lens, scale)
    S_pad = _round_up(S, 128)
    for cand in (int(block_k), 512, 384, 256, 128):
        if cand <= int(block_k) and S_pad % cand == 0:
            block_k = cand
            break
    else:
        block_k = min(int(block_k), S_pad)
    d_pad = _round_up(d, 128)
    qp = q if d == d_pad else jnp.pad(q, ((0, 0), (0, 0), (0, d_pad - d)))
    def _pad_cache(c):
        if S == S_pad and d == d_pad:
            return c
        return jnp.pad(c, ((0, 0), (0, S_pad - S), (0, 0),
                           (0, d_pad - d)))
    kp, vp = _pad_cache(k_cache), _pad_cache(v_cache)
    kernel = functools.partial(_decode_kernel, scale, block_k)
    qo_spec = pl.BlockSpec((1, 1, d_pad), lambda bi, hi, ki: (bi, hi, 0),
                           memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, 1, d_pad),
                           lambda bi, hi, ki: (bi, ki, hi, 0),
                           memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=(b, h, S_pad // block_k),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  qo_spec, kv_spec, kv_spec],
        out_specs=qo_spec,
        out_shape=_sds((b, h, d_pad), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((1, 128), _f32),
                        pltpu.VMEM((1, 128), _f32),
                        pltpu.VMEM((1, d_pad), _f32)],
        compiler_params=_compiler_params(),
        interpret=interpret_mode(),
    )(cache_lens, qp, kp, vp)
    return out[:, :, :d]


def gather_paged_kv(pool, block_tables):
    """Materialize a paged cache as the contiguous layout.

    ``pool``: ``(num_blocks, block_size, heads, head_dim)`` (one layer,
    one of K/V); ``block_tables``: ``(batch, max_blocks)`` int.  Returns
    ``(batch, max_blocks * block_size, heads, head_dim)`` — positions
    map as ``p -> (table[p // bs], p % bs)``, so the gathered array is
    elementwise IDENTICAL to a contiguous cache at every valid position
    (garbage-block rows land at masked positions).  This is the off-TPU
    paged path and the parity bridge to the contiguous kernels.
    """
    b, nb = block_tables.shape
    bs, h, d = pool.shape[1:]
    return pool[block_tables].reshape(b, nb * bs, h, d)


def _decode_paged_kernel(scale, bs, len_ref, tbl_ref, q_ref, k_ref, v_ref,
                         o_ref, m_scr, l_scr, acc_scr):
    """Single-query decode over a BLOCK TABLE: identical online-softmax
    math to :func:`_decode_kernel`, but the kv BlockSpec's index_map
    reads the physical block id from the scalar-prefetched table, so the
    DMA engine walks ``tbl[b, ki]`` instead of a contiguous row."""
    b = pl.program_id(0)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr[:], _MASK)
        l_scr[:] = jnp.zeros_like(l_scr[:])
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    @pl.when(ki * bs < len_ref[b])
    def _compute():
        q = q_ref[0]                              # (1, d_pad)
        k = k_ref[0, :, 0, :]                     # (bs, d_pad)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=_f32) * scale
        k_pos = ki * bs + jax.lax.broadcasted_iota(
            jnp.int32, (1, bs), 1)
        valid = k_pos < len_ref[b]
        s = jnp.where(valid, s, _MASK)
        m_prev = m_scr[:, :1]
        m_cur = jnp.maximum(jnp.max(s, axis=1, keepdims=True), m_prev)
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.where(valid, jnp.exp(s - m_cur), 0.0)
        l_cur = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, :, 0, :],
            (((1,), (0,)), ((), ())), preferred_element_type=_f32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_cur, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_cur, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def flash_attention_decode_paged(q, k_pool, v_pool, block_tables,
                                 cache_lens, softmax_scale=None):
    """Single-token decode attention over a paged KV pool.

    ``q``: ``(batch, heads, head_dim)``; ``k_pool``/``v_pool``:
    ``(num_blocks, block_size, heads, head_dim)`` — ONE layer's K (or V)
    blocks from :class:`apex_tpu.serving.PagedKVCache`;
    ``block_tables``: ``(batch, max_blocks)`` int32 physical block ids
    per logical block (garbage-padded rows use block 0);
    ``cache_lens``: ``(batch,)`` valid lengths.

    Semantics are exactly :func:`flash_attention_decode` on the gathered
    contiguous cache — and the off-TPU path literally IS that: gather +
    the same masked reference, which is what makes paged decode
    token-bitwise-identical to the contiguous engine on CPU.  On TPU a
    Pallas kernel walks the block table via scalar prefetch
    (``PrefetchScalarGridSpec``) so the gather never materializes.
    """
    b, h, d = q.shape
    bs = k_pool.shape[1]
    nb = block_tables.shape[1]
    scale = float(softmax_scale if softmax_scale is not None
                  else d ** -0.5)
    cache_lens = cache_lens.astype(jnp.int32)
    block_tables = block_tables.astype(jnp.int32)
    if not use_pallas():
        return flash_attention_decode_reference(
            q, gather_paged_kv(k_pool, block_tables),
            gather_paged_kv(v_pool, block_tables), cache_lens, scale)
    d_pad = _round_up(d, 128)
    qp = q if d == d_pad else jnp.pad(q, ((0, 0), (0, 0), (0, d_pad - d)))

    def _pad_pool(c):
        if d == d_pad:
            return c
        return jnp.pad(c, ((0, 0), (0, 0), (0, 0), (0, d_pad - d)))

    kernel = functools.partial(_decode_paged_kernel, scale, bs)
    qo_spec = pl.BlockSpec((1, 1, d_pad),
                           lambda bi, hi, ki, lens, tbl: (bi, hi, 0),
                           memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec(
        (1, bs, 1, d_pad),
        lambda bi, hi, ki, lens, tbl: (tbl[bi, ki], 0, hi, 0),
        memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, nb),
        in_specs=[qo_spec, kv_spec, kv_spec],
        out_specs=qo_spec,
        scratch_shapes=[pltpu.VMEM((1, 128), _f32),
                        pltpu.VMEM((1, 128), _f32),
                        pltpu.VMEM((1, d_pad), _f32)])
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_sds((b, h, d_pad), q.dtype, q),
        compiler_params=_compiler_params(),
        interpret=interpret_mode(),
    )(cache_lens, block_tables, qp, _pad_pool(k_pool), _pad_pool(v_pool))
    return out[:, :, :d]


def flash_attention_chunk_paged(q, k_pool, v_pool, block_tables,
                                q_positions, softmax_scale=None):
    """Multi-query decode attention over a paged pool (chunked prefill
    and speculative verification).

    ``q``: ``(batch, heads, chunk, head_dim)`` — ``chunk`` query tokens
    per sequence, NOT necessarily starting at position 0;
    ``q_positions``: ``(batch, chunk)`` each query's absolute position.
    Key position ``kp`` is visible to query ``j`` iff
    ``kp <= q_positions[:, j]`` — causality over the whole cached
    context, matching prefill exactly for in-order chunks.  Pools and
    tables as in :func:`flash_attention_decode_paged`; the chunk's own
    K/V must be written to the pool before the call.

    Runs as a masked jnp gather on every backend (chunks are short and
    wide enough that XLA fuses this well; the single-token fast path is
    the Pallas kernel above).  f32 scores/accumulation as everywhere.
    """
    b, h, c, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    k = gather_paged_kv(k_pool, block_tables)     # (b, S, h, d)
    v = gather_paged_kv(v_pool, block_tables)
    S = k.shape[1]
    s = jnp.einsum("bhcd,bshd->bhcs", q.astype(_f32),
                   k.astype(_f32)) * scale
    valid = (jnp.arange(S)[None, None, None, :]
             <= q_positions[:, None, :, None])    # (b, 1, c, S)
    s = jnp.where(valid, s, _MASK)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(valid, p, 0.0)
    o = jnp.einsum("bhcs,bshd->bhcd", p, v.astype(_f32))
    return o.astype(q.dtype)


def quantize_kv_blocks(blocks):
    """Int8 scale-per-block quantization of KV blocks (the EQuARX idiom
    from ``utils.compressed_allreduce``, applied to the paged cache).

    ``blocks``: ``(..., block_size, heads, head_dim)`` float — any
    leading batch/layer/kv axes.  The scale is shared across the block's
    positions and head_dim but kept PER HEAD (attention scores are
    per-head dot products, so a hot head cannot inflate a cold head's
    quantization step).  Returns ``(q8, scales)`` with ``q8`` int8 of
    ``blocks.shape`` and ``scales`` f32 of ``blocks.shape[:-3] +
    (heads,)``.  All-zero blocks get scale 1.0, so dequantization is
    exact zeros — the zero-on-alloc invariant the quantized pool relies
    on for deterministic whole-block requantization.
    """
    x = blocks.astype(_f32)
    amax = jnp.max(jnp.abs(x), axis=(-3, -1))        # (..., heads)
    scale = amax / 127.0
    scale = jnp.where(scale > 0, scale, 1.0)
    q8 = jnp.clip(jnp.round(x / scale[..., None, :, None]),
                  -127, 127).astype(jnp.int8)
    return q8, scale


def dequantize_kv_blocks(q8, scales, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv_blocks`: ``q8``
    ``(..., block_size, heads, head_dim)`` int8, ``scales``
    ``(..., heads)`` f32, returns ``dtype``."""
    return (q8.astype(_f32) * scales[..., None, :, None]).astype(dtype)


def gather_paged_kv_quant(pool, scales, block_tables,
                          dtype=jnp.float32):
    """:func:`gather_paged_kv` for an int8 pool: gather the table's
    blocks AND their per-block scales, dequantize only what was
    gathered, and return the contiguous layout in ``dtype``.

    ``pool``: ``(num_blocks, block_size, heads, head_dim)`` int8 (one
    layer, one of K/V); ``scales``: ``(num_blocks, heads)`` f32;
    ``block_tables``: ``(batch, max_blocks)`` int.  Returns
    ``(batch, max_blocks * block_size, heads, head_dim)``.
    """
    b, nb = block_tables.shape
    bs, h, d = pool.shape[1:]
    deq = dequantize_kv_blocks(pool[block_tables],
                               scales[block_tables], dtype)
    return deq.reshape(b, nb * bs, h, d)


def flash_attention_decode_paged_quant(q, k_pool, v_pool, k_scales,
                                       v_scales, block_tables,
                                       cache_lens, softmax_scale=None):
    """Single-token decode attention over an int8 paged pool.

    Same contract as :func:`flash_attention_decode_paged` with the pool
    quantized: ``k_pool``/``v_pool`` int8, ``k_scales``/``v_scales``
    ``(num_blocks, heads)`` f32.  Dequantization rides the gather path —
    only the table's blocks are dequantized (into f32, the same
    precision the reference's scores/PV already accumulate in), then the
    masked reference runs unchanged, so the quantized decode differs
    from the bf16/f32 decode ONLY by the per-block rounding, never by
    schedule.  A fused Pallas kernel that dequantizes in-VMEM per block
    is a straightforward extension of ``_decode_paged_kernel`` (the
    scale is one scalar per (block, head)); the gather path keeps CI
    exact and backend-uniform.
    """
    cache_lens = cache_lens.astype(jnp.int32)
    block_tables = block_tables.astype(jnp.int32)
    scale = float(softmax_scale if softmax_scale is not None
                  else q.shape[-1] ** -0.5)
    return flash_attention_decode_reference(
        q, gather_paged_kv_quant(k_pool, k_scales, block_tables, _f32),
        gather_paged_kv_quant(v_pool, v_scales, block_tables, _f32),
        cache_lens, scale)


def flash_attention_chunk_paged_quant(q, k_pool, v_pool, k_scales,
                                      v_scales, block_tables,
                                      q_positions, softmax_scale=None):
    """Multi-query decode attention over an int8 paged pool — the
    quantized :func:`flash_attention_chunk_paged` (chunked prefill on a
    quantized cache).  Same masked-gather math with the gather
    dequantizing per block."""
    b, h, c, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    k = gather_paged_kv_quant(k_pool, k_scales, block_tables, _f32)
    v = gather_paged_kv_quant(v_pool, v_scales, block_tables, _f32)
    S = k.shape[1]
    s = jnp.einsum("bhcd,bshd->bhcs", q.astype(_f32), k) * scale
    valid = (jnp.arange(S)[None, None, None, :]
             <= q_positions[:, None, :, None])    # (b, 1, c, S)
    s = jnp.where(valid, s, _MASK)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(valid, p, 0.0)
    o = jnp.einsum("bhcs,bshd->bhcd", p, v)
    return o.astype(q.dtype)


def flash_attention(q, k, v, causal=False, softmax_scale=None,
                    kv_seqlens=None, block_q=1024, block_k=1024,
                    dropout=0.0, dropout_seed=None):
    """Fused attention over ``(batch, heads, seq, head_dim)`` operands.

    ``causal=True`` applies the upper-triangular mask (requires
    ``sq == sk``); ``kv_seqlens`` is an optional ``(batch,)`` int array of
    valid key lengths (True padding parity with the reference's
    ``cu_seqlens`` packing).  ``softmax_scale`` defaults to
    ``head_dim**-0.5``.

    ``dropout``: probability dropout fused into the kernel (reference:
    apex's philox-fused attention dropout) — the keep mask is a
    counter-hash of ``(dropout_seed, batch*head, q_pos, k_pos)``
    regenerated in the backward, so memory stays O(s).  ``dropout_seed``
    is an int (or traced int scalar); fold the training step counter in
    for fresh masks per step.  The mask is identical on every backend
    and for every block-size choice.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if causal and sq != sk:
        raise ValueError("causal flash attention requires sq == sk")
    scale = float(softmax_scale if softmax_scale is not None
                  else d ** -0.5)
    rate = float(dropout)
    if not 0.0 <= rate < 1.0:
        raise ValueError(f"dropout must be in [0, 1), got {rate}")
    if rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout > 0 needs dropout_seed")
    if not use_pallas():
        mask = None
        if rate > 0.0:
            mask = dropout_keep_scale(dropout_seed, b * h, sq, sk,
                                      rate).reshape(b, h, sq, sk)
        return flash_attention_reference(q, k, v, causal, scale,
                                         kv_seqlens, dropout_mask=mask)
    has_lens = kv_seqlens is not None
    if kv_seqlens is None:
        kv_seqlens = jnp.full((b,), sk, jnp.int32)
    seed = jnp.reshape(jnp.asarray(
        0 if dropout_seed is None else dropout_seed, jnp.int32), (1,))
    # big default blocks amortize Mosaic grid-step overhead: the
    # round-5 on-chip sweep (tools/sweep_flash.py) has (1024,1024)
    # beating (512,512) by ~12% at seq 1024/2048 fwd+bwd and (512,512)
    # optimal at seq 512 — grid-step overhead dominates the causal
    # block-skip saving.  Pick the largest candidate that divides the
    # padded sequence, so arbitrary lengths (e.g. 640) don't inflate
    # padding to a whole large block.
    def _fit(requested, s):
        s_pad = _round_up(s, 128)
        for cand in (requested, 512, 384, 256, 128):
            if cand <= requested and s_pad % cand == 0:
                return cand
        return min(requested, s_pad)
    block_q = _fit(int(block_q), sq)
    block_k = _fit(int(block_k), sk)
    # static no-mask fast path: dense full-length non-causal attention
    # with block-aligned extents (post-_fit) needs NO iota/compare/
    # select passes in any of the three kernels (zero-padding of
    # head_dim is harmless: padded lanes contribute 0 to every dot)
    masked = bool(causal or has_lens or sq % block_q or sk % block_k)
    return _flash(q, k, v, kv_seqlens, seed, bool(causal), scale,
                  block_q, block_k, rate, masked)
