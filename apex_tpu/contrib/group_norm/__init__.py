"""NHWC GroupNorm — TPU rebuild of ``apex/contrib/group_norm/``
(``group_norm.py`` + ``csrc/group_norm/*.cu``, the diffusion-model
kernels tuned for Stable-Diffusion shapes).

The reference exists because cuDNN GroupNorm wants NCHW; its kernels
normalize channels-last activations directly and optionally fuse the
SiLU/Swish activation.  On TPU channels-last is already the natural
layout and XLA fuses the normalize+affine+swish chain, so the module is
a jnp composition with the reference's exact surface:
``GroupNorm(num_groups, num_channels, eps, affine, act="" | "silu" |
"swish")`` over ``(N, H, W, C)`` inputs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["GroupNorm", "group_norm_nhwc"]

_f32 = jnp.float32


def group_norm_nhwc(x, num_groups, weight=None, bias=None, eps=1e-5,
                    act=""):
    """GroupNorm over the trailing channel axis of ``(..., C)`` NHWC
    input; stats are per (sample, group) over all spatial positions."""
    c = x.shape[-1]
    if c % num_groups:
        raise ValueError(f"channels {c} not divisible by groups "
                         f"{num_groups}")
    orig_dtype = x.dtype
    n = x.shape[0]
    xf = x.astype(_f32).reshape(n, -1, num_groups, c // num_groups)
    mean = jnp.mean(xf, axis=(1, 3), keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=(1, 3), keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(x.shape)
    if weight is not None:
        y = y * weight.astype(_f32)
    if bias is not None:
        y = y + bias.astype(_f32)
    if act in ("silu", "swish"):
        y = y * jax.nn.sigmoid(y)
    elif act:
        raise ValueError(f"unsupported act {act!r}")
    return y.astype(orig_dtype)


class GroupNorm:
    """apex ``contrib.group_norm.GroupNorm`` (NHWC, optional fused
    swish).  Functional-param module: ``params = m.init_params()``,
    ``y = m(params, x)``."""

    def __init__(self, num_groups, num_channels, eps=1e-5, affine=True,
                 act="", param_dtype=jnp.float32):
        self.num_groups = int(num_groups)
        self.num_channels = int(num_channels)
        self.eps = float(eps)
        self.affine = bool(affine)
        self.act = act
        self.param_dtype = param_dtype

    def init_params(self):
        if not self.affine:
            return {}
        return {"weight": jnp.ones((self.num_channels,), self.param_dtype),
                "bias": jnp.zeros((self.num_channels,), self.param_dtype)}

    def __call__(self, params, x):
        w = params.get("weight") if self.affine else None
        b = params.get("bias") if self.affine else None
        return group_norm_nhwc(x, self.num_groups, w, b, self.eps,
                               self.act)

    apply = __call__
