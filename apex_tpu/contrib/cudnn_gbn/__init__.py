"""Group batch norm, cudnn-frontend flavor — TPU rebuild of
``apex/contrib/cudnn_gbn/`` (``batch_norm.py`` + ``norm_sample.cpp``).

The reference's ``GroupBatchNorm2d`` is the same feature as
``apex/contrib/groupbn`` — NHWC batch norm whose statistics are shared
across a group of devices — implemented through cudnn's norm sampler
instead of the hand-written kernels.  On TPU both reduce to one design
(local Welford + psum over the group mesh axis), so this module provides
the ``cudnn_gbn`` surface over :mod:`apex_tpu.contrib.groupbn`'s
implementation; ``group_size`` maps to the size of the named mesh axis
the call runs under.
"""

from __future__ import annotations

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

__all__ = ["GroupBatchNorm2d"]


class GroupBatchNorm2d(BatchNorm2d_NHWC):
    """Reference ctor: ``GroupBatchNorm2d(num_features, group_size=1,
    group_rank=..., fuse_relu=False)``; group membership here is the mesh
    axis named by ``axis_name`` (group_size/rank come from the mesh)."""

    def __init__(self, num_features, group_size=1, group_rank=None,
                 bn_group=None, fuse_relu=False, axis_name=None, **kw):
        del group_rank
        group = bn_group if bn_group is not None else group_size
        super().__init__(num_features, fuse_relu=fuse_relu,
                         bn_group=group, axis_name=axis_name, **kw)
