"""ASP (automatic structured sparsity) — TPU rebuild of
``apex/contrib/sparsity/`` (``asp.py``, ``sparse_masklib.py``,
``permutation_lib.py`` + its CUDA search kernels).

The reference finds 2:4 magnitude masks for prunable weights, masks
them, and re-applies the masks after every optimizer step (the optimizer
step hook).  Functional JAX has no in-place hooks, so the surface is
explicit: ``compute_sparse_masks`` builds the mask pytree,
``apply_masks`` multiplies, and ``wrap_optimizer_step`` returns a step
function that re-masks after the update — same training loop shape as
``ASP.init_optimizer_for_pruning``.

2:4 on TPU note: XLA has no sparse-MXU path today, so the win ASP
preserves is model-compression/accuracy parity, not step time; the mask
semantics (per 4 consecutive weights along the input dim, keep the top
2 magnitudes) match ``sparse_masklib.create_mask(pattern="m4n2_1d")``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["create_mask", "ASP", "permutation_search",
           "apply_input_permutation", "invert_permutation",
           "magnitude_retained"]


def create_mask(tensor, pattern="m4n2_1d"):
    """Boolean keep-mask with the reference's ``m4n2_1d`` pattern: in
    every 4 consecutive elements of the last axis, keep the 2 largest
    magnitudes."""
    if pattern != "m4n2_1d":
        raise ValueError(f"unsupported pattern {pattern!r}")
    if tensor.shape[-1] % 4:
        raise ValueError("last dim must be divisible by 4 for m4n2")
    mag = jnp.abs(tensor).reshape(tensor.shape[:-1] + (-1, 4))
    # rank within each group of 4; keep the top 2
    order = jnp.argsort(mag, axis=-1)            # ascending
    ranks = jnp.argsort(order, axis=-1)
    keep = ranks >= 2
    return keep.reshape(tensor.shape)


def _default_prunable(path, leaf):
    """apex default: prune 2-D+ weights with input dim divisible by 4
    and both dims >= 32 (skips tiny/vector params and embeddings are the
    caller's policy via ``is_prunable``)."""
    return (leaf.ndim >= 2 and leaf.shape[-1] % 4 == 0
            and leaf.shape[-1] >= 32 and leaf.shape[-2] >= 32)


class ASP:
    """apex ``ASP`` adapted to functional params.

    ``asp = ASP(); masks = asp.compute_sparse_masks(params)``;
    ``params = asp.apply_masks(params, masks)``;
    ``step = asp.wrap_optimizer_step(opt.step, masks)``.
    """

    def __init__(self, mask_calculator="m4n2_1d", is_prunable=None):
        self.pattern = mask_calculator
        self.is_prunable = is_prunable or _default_prunable

    def compute_sparse_masks(self, params):
        def mask_leaf(path, leaf):
            p = jax.tree_util.keystr(path)
            if self.is_prunable(p, leaf):
                return create_mask(leaf, self.pattern)
            return jnp.ones(leaf.shape, bool)

        return jax.tree_util.tree_map_with_path(mask_leaf, params)

    @staticmethod
    def apply_masks(params, masks):
        return jax.tree_util.tree_map(
            lambda p, m: jnp.where(m, p, jnp.zeros((), p.dtype)), params,
            masks)

    def wrap_optimizer_step(self, step_fn, masks):
        """Re-apply masks after every update (the reference's optimizer
        hook): ``wrapped(grads, params, state, **kw)``."""

        def wrapped(grads, params, state, **kw):
            new_params, new_state = step_fn(grads, params, state, **kw)
            return self.apply_masks(new_params, masks), new_state

        return wrapped


# -- permutation search (reference: apex permutation_lib.py) ----------------

def magnitude_retained(weight) -> float:
    """Fraction of |weight| magnitude a 2:4 mask keeps (the permutation
    search objective — reference ``permutation_lib``'s efficacy metric)."""
    import numpy as np

    w = np.abs(np.asarray(weight, np.float32))
    if w.shape[-1] % 4:
        raise ValueError("last dim must be divisible by 4 (m4n2_1d "
                         "groups, matching create_mask)")
    total = float(w.sum())
    if total == 0.0:
        return 1.0
    g = w.reshape(*w.shape[:-1], -1, 4)
    kept = np.sort(g, axis=-1)[..., 2:].sum()
    return float(kept) / total


def permutation_search(weight, max_passes: int = 4, seed: int = 0):
    """Find an input-channel permutation improving 2:4 retained magnitude.

    Reference ``permutation_lib.py`` searches channel permutations with
    CUDA kernels so that magnitude pruning destroys less signal; this is
    the host-side equivalent: bounded greedy column-swap passes (accept
    any swap between different groups-of-4 that increases the kept
    magnitude), deterministic for a given seed.

    Returns ``(perm, improved_retained)`` where ``perm`` is an index
    array with ``weight[:, perm]`` the permuted matrix.  Offline tool —
    numpy, not jit; run once before training like the reference.
    """
    import numpy as np

    w = np.abs(np.asarray(weight, np.float32))
    n_out, n_in = w.shape
    if n_in % 4:
        raise ValueError("input dim must be divisible by 4")
    perm = np.arange(n_in)
    rng = np.random.RandomState(seed)

    def group_kept(cols):
        # kept magnitude of each group given column set (n_out, 4)
        g = w[:, cols]
        return np.sort(g, axis=-1)[:, 2:].sum()

    groups = perm.reshape(-1, 4).copy()
    kept = np.array([group_kept(g) for g in groups])
    n_groups = len(groups)
    for _ in range(max_passes):
        improved = False
        # bounded candidate sampling keeps this O(passes * n_in) instead
        # of O(n_in^2) full pairwise search
        order = rng.permutation(n_groups)
        for gi in order:
            gj = int(rng.randint(n_groups))
            if gi == gj:
                continue
            base = kept[gi] + kept[gj]
            best = (None, 0.0)
            for a in range(4):
                for b in range(4):
                    groups[gi][a], groups[gj][b] = \
                        groups[gj][b], groups[gi][a]
                    trial = group_kept(groups[gi]) + group_kept(groups[gj])
                    gain = trial - base
                    if gain > best[1] + 1e-9:
                        best = ((a, b), gain)
                    groups[gi][a], groups[gj][b] = \
                        groups[gj][b], groups[gi][a]
            if best[0] is not None:
                a, b = best[0]
                groups[gi][a], groups[gj][b] = groups[gj][b], groups[gi][a]
                kept[gi] = group_kept(groups[gi])
                kept[gj] = group_kept(groups[gj])
                improved = True
        if not improved:
            break
    perm = groups.reshape(-1)
    return perm, float(kept.sum()) / max(float(w.sum()), 1e-30)


def apply_input_permutation(weight, perm):
    """``weight[:, perm]`` — permute input channels before masking.  The
    consuming layer's INPUT must be permuted identically (or the
    producing layer's output channels — reference propagates through the
    model graph; here the caller owns that wiring)."""
    return weight[:, jnp.asarray(perm)]


def invert_permutation(perm):
    import numpy as np

    perm = np.asarray(perm)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    return inv
