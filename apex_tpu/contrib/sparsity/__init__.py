"""ASP (automatic structured sparsity) — TPU rebuild of
``apex/contrib/sparsity/`` (``asp.py``, ``sparse_masklib.py``; the CUDA
permutation-search kernels are an accuracy refinement, not ported).

The reference finds 2:4 magnitude masks for prunable weights, masks
them, and re-applies the masks after every optimizer step (the optimizer
step hook).  Functional JAX has no in-place hooks, so the surface is
explicit: ``compute_sparse_masks`` builds the mask pytree,
``apply_masks`` multiplies, and ``wrap_optimizer_step`` returns a step
function that re-masks after the update — same training loop shape as
``ASP.init_optimizer_for_pruning``.

2:4 on TPU note: XLA has no sparse-MXU path today, so the win ASP
preserves is model-compression/accuracy parity, not step time; the mask
semantics (per 4 consecutive weights along the input dim, keep the top
2 magnitudes) match ``sparse_masklib.create_mask(pattern="m4n2_1d")``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["create_mask", "ASP"]


def create_mask(tensor, pattern="m4n2_1d"):
    """Boolean keep-mask with the reference's ``m4n2_1d`` pattern: in
    every 4 consecutive elements of the last axis, keep the 2 largest
    magnitudes."""
    if pattern != "m4n2_1d":
        raise ValueError(f"unsupported pattern {pattern!r}")
    if tensor.shape[-1] % 4:
        raise ValueError("last dim must be divisible by 4 for m4n2")
    mag = jnp.abs(tensor).reshape(tensor.shape[:-1] + (-1, 4))
    # rank within each group of 4; keep the top 2
    order = jnp.argsort(mag, axis=-1)            # ascending
    ranks = jnp.argsort(order, axis=-1)
    keep = ranks >= 2
    return keep.reshape(tensor.shape)


def _default_prunable(path, leaf):
    """apex default: prune 2-D+ weights with input dim divisible by 4
    and both dims >= 32 (skips tiny/vector params and embeddings are the
    caller's policy via ``is_prunable``)."""
    return (leaf.ndim >= 2 and leaf.shape[-1] % 4 == 0
            and leaf.shape[-1] >= 32 and leaf.shape[-2] >= 32)


class ASP:
    """apex ``ASP`` adapted to functional params.

    ``asp = ASP(); masks = asp.compute_sparse_masks(params)``;
    ``params = asp.apply_masks(params, masks)``;
    ``step = asp.wrap_optimizer_step(opt.step, masks)``.
    """

    def __init__(self, mask_calculator="m4n2_1d", is_prunable=None):
        self.pattern = mask_calculator
        self.is_prunable = is_prunable or _default_prunable

    def compute_sparse_masks(self, params):
        def mask_leaf(path, leaf):
            p = jax.tree_util.keystr(path)
            if self.is_prunable(p, leaf):
                return create_mask(leaf, self.pattern)
            return jnp.ones(leaf.shape, bool)

        return jax.tree_util.tree_map_with_path(mask_leaf, params)

    @staticmethod
    def apply_masks(params, masks):
        return jax.tree_util.tree_map(
            lambda p, m: jnp.where(m, p, jnp.zeros((), p.dtype)), params,
            masks)

    def wrap_optimizer_step(self, step_fn, masks):
        """Re-apply masks after every update (the reference's optimizer
        hook): ``wrapped(grads, params, state, **kw)``."""

        def wrapped(grads, params, state, **kw):
            new_params, new_state = step_fn(grads, params, state, **kw)
            return self.apply_masks(new_params, masks), new_state

        return wrapped
