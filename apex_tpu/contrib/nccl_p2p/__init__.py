"""NCCL-P2P halo exchange surface — TPU rebuild of
``apex/contrib/nccl_p2p/`` (``nccl_p2p.py`` + ``nccl_p2p_cuda.cu``).

The reference wraps ``ncclSend``/``ncclRecv`` pairs into
``left_right_halo_exchange``: every rank sends its left output halo to
the left neighbor and its right output halo to the right neighbor, and
receives the neighbors' halos back.  On TPU the transport is
``lax.ppermute`` over an ICI mesh axis — same wire pattern, compiled as
a collective-permute; edge ranks receive zeros (the reference leaves
edge buffers untouched and masks them in the caller).

Call inside ``shard_map`` with ``axis_name`` in scope.
"""

from __future__ import annotations

import jax
from apex_tpu.utils.collectives import axis_size as _axis_size

__all__ = ["left_right_halo_exchange", "left_right_halo_exchange_inplace",
           "get_unique_nccl_id", "init_nccl_comm"]


def left_right_halo_exchange(left_output_halo, right_output_halo,
                             axis_name: str = "spatial"):
    """Send left/right halos to the respective neighbors.

    Returns ``(left_input_halo, right_input_halo)``: what THIS device
    receives from its left and right neighbor (zeros at the edges) —
    reference ``nccl_p2p.left_right_halo_exchange``.
    """
    n = _axis_size(axis_name)
    right_from_left = [(i, i + 1) for i in range(n - 1)]   # i -> i+1
    left_from_right = [(i + 1, i) for i in range(n - 1)]   # i -> i-1
    # my RIGHT output halo travels right: arrives as neighbor's LEFT input
    left_input_halo = jax.lax.ppermute(right_output_halo, axis_name,
                                       right_from_left)
    # my LEFT output halo travels left: arrives as neighbor's RIGHT input
    right_input_halo = jax.lax.ppermute(left_output_halo, axis_name,
                                        left_from_right)
    return left_input_halo, right_input_halo


def left_right_halo_exchange_inplace(left_output_halo, right_output_halo,
                                     left_input_halo, right_input_halo,
                                     axis_name: str = "spatial"):
    """Reference in-place variant; functional JAX has no aliasing, so the
    received halos are returned (the in-place buffers are ignored)."""
    del left_input_halo, right_input_halo
    return left_right_halo_exchange(left_output_halo, right_output_halo,
                                    axis_name)


def get_unique_nccl_id(n: int = 1):
    """Reference bootstrap helper; meaningless on TPU (the mesh IS the
    communicator).  Kept so call sites import cleanly."""
    return [0] * n


def init_nccl_comm(nccl_id=None, rank=None, world_size=None):
    """No-op: XLA collectives need no communicator objects."""
    return None
