"""OpenFold fused kernels — TPU rebuild of
``apex/contrib/openfold_triton/`` (Triton kernels NVIDIA wrote for
OpenFold/AlphaFold2 training: evoformer MHA with additive pair bias +
mask, LayerNorm tuned for OpenFold's small trailing shapes, and
``FusedAdamSWA`` — Adam + stochastic weight averaging in one pass).

TPU mapping:

* :func:`attention_core` — OpenFold's MHA contract (additive biases
  broadcast over heads/rows, -inf masking) over the framework's
  attention ops: the Pallas flash kernel when no bias is present, the
  fused reference path (same masking semantics) when biases make the
  score matrix explicit.
* :class:`LayerNormSmallShapeOptImpl` — OpenFold's LN entry; delegates
  to the Pallas fused LayerNorm (``apex_tpu.ops.layer_norm``), which
  already optimizes the small-hidden case via row blocking.
* :class:`FusedAdamSWA` — FusedAdam step + SWA accumulation fused at the
  packed-bucket level.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm_affine
from apex_tpu.optimizers import FusedAdam

__all__ = ["attention_core", "LayerNormSmallShapeOptImpl", "FusedAdamSWA"]

_f32 = jnp.float32


def attention_core(q, k, v, mask=None, bias=None, inf: float = 1e9):
    """OpenFold evoformer attention (reference ``mha.py``).

    ``q, k, v``: ``(*batch, heads, seq_q|seq_k, head_dim)``; ``mask``:
    broadcastable boolean/0-1 tensor over ``(*batch, 1, 1, seq_k)`` with
    1 = keep (OpenFold convention); ``bias``: additive pair bias
    broadcastable over the score shape.  Scaling by ``head_dim**-0.5``
    is applied here, like the reference kernel.
    """
    *batch, h, sq, d = q.shape
    sk = k.shape[-2]
    qr = q.reshape(-1, h, sq, d)
    kr = k.reshape(-1, h, sk, d)
    vr = v.reshape(-1, h, sk, d)
    if mask is None and bias is None:
        out = flash_attention(qr, kr, vr, causal=False)
        return out.reshape(*batch, h, sq, d)
    # biasful path: explicit scores with OpenFold's -inf masking
    s = jnp.einsum("bhqd,bhkd->bhqk", qr.astype(_f32),
                   kr.astype(_f32)) * d ** -0.5
    s = s.reshape(*batch, h, sq, sk)
    if bias is not None:
        s = s + bias.astype(_f32)
    if mask is not None:
        s = s - (1.0 - mask.astype(_f32)) * inf
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("...hqk,...hkd->...hqd", p,
                     v.reshape(*batch, h, sk, d).astype(_f32))
    return out.astype(q.dtype)


class LayerNormSmallShapeOptImpl:
    """Reference ``LayerNormSmallShapeOptImpl.apply(x, w, b, eps)`` —
    the autograd entry OpenFold swaps in; here the Pallas fused LN."""

    @staticmethod
    def apply(x, weight, bias, eps: float = 1e-5):
        return fused_layer_norm_affine(x, weight, bias,
                                       normalized_shape=(x.shape[-1],),
                                       eps=eps)


class FusedAdamSWA:
    """Adam + stochastic weight averaging (reference
    ``fused_adam_swa.py``: one kernel updates params AND the SWA running
    average).  Functional form: state carries the packed Adam state plus
    ``swa`` params and a sample count; ``swa_params`` averages every
    ``swa_freq`` steps after ``swa_start``.
    """

    def __init__(self, lr=1e-3, swa_start: int = 0, swa_freq: int = 1,
                 **adam_kw):
        self.adam = FusedAdam(lr=lr, **adam_kw)
        self.swa_start = int(swa_start)
        self.swa_freq = max(int(swa_freq), 1)

    def init(self, params):
        return {
            "adam": self.adam.init(params),
            "swa": jax.tree_util.tree_map(
                lambda p: p.astype(_f32), params),
            "n_avg": jnp.zeros((), jnp.int32),
        }

    def step(self, grads, params, state, **kw):
        new_params, adam_state = self.adam.step(grads, params,
                                                state["adam"], **kw)
        step_count = adam_state["step"]
        do_avg = jnp.logical_and(
            step_count > self.swa_start,
            (step_count - 1 - self.swa_start) % self.swa_freq == 0)
        n = state["n_avg"]
        new_n = jnp.where(do_avg, n + 1, n)

        # divisor guarded: on non-averaging steps new_n can be 0 and the
        # branch is discarded by the where, but 0-div would still poison
        # jax_debug_nans / differentiation through step
        denom = jnp.maximum(new_n, 1).astype(_f32)

        def avg(s, p):
            # running mean over sampled checkpoints (torch SWA formula)
            upd = s + (p.astype(_f32) - s) / denom
            return jnp.where(do_avg, upd, s)

        new_swa = jax.tree_util.tree_map(avg, state["swa"], new_params)
        return new_params, {"adam": adam_state, "swa": new_swa,
                            "n_avg": new_n}

    def swa_params(self, state, like=None):
        """The averaged params (cast back to the model dtypes)."""
        src = state["swa"]
        if like is None:
            return src
        return jax.tree_util.tree_map(
            lambda s, p: s.astype(p.dtype), src, like)
