"""apex.contrib.xentropy parity shim (implementation in
``apex_tpu.ops.xentropy``)."""

from apex_tpu.ops.xentropy import (SoftmaxCrossEntropyLoss,
                                   softmax_cross_entropy_loss)

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss"]
