"""FMHA — TPU rebuild of ``apex/contrib/fmha/fmha.py`` (MLPerf-BERT
fused multi-head attention, ``fmha/src/*.cu``).

The reference packs variable-length sequences into one token axis and
dispatches per-seqlen CUDA templates (128/256/384/512).  Here the packed
``cu_seqlens`` surface is kept, but the core is the Pallas flash-attention
kernel, which has no sequence-length cap: the packed tokens are scattered
to a dense ``(batch, max_s)`` layout, attended with per-row ``kv_seqlens``
masking (identical semantics to the packed kernels — keys beyond a row's
length contribute nothing), and gathered back to the packed layout.

``fmha(qkv, cu_seqlens, max_s)`` with ``qkv`` of shape
``(total_tokens, 3, heads, head_dim)`` mirrors ``FMHAFun.apply``.
Probability dropout (``p_dropout > 0``) is fused into the kernel (the
reference's philox-fused dropout); ``dropout_rng`` seeds the
counter-hash keep mask.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention

__all__ = ["fmha", "FMHAFun"]


def _token_coords(cu_seqlens, total):
    """Per-token (batch_row, offset) for packed layout; cu_seqlens is
    ``(batch+1,)`` monotone int32 with cu_seqlens[-1] == total tokens."""
    tok = jnp.arange(total)
    row = jnp.searchsorted(cu_seqlens, tok, side="right") - 1
    off = tok - cu_seqlens[row]
    return row, off


def fmha(qkv, cu_seqlens, max_s, p_dropout=0.0, is_training=True,
         causal=False, dropout_rng=None):
    """Packed fused MHA: ``qkv (total, 3, h, d)`` -> ``(total, h, d)``.

    ``cu_seqlens``: ``(batch+1,)`` cumulative sequence starts (apex
    convention); ``max_s``: static maximum sequence length (defines the
    dense scratch layout, like the reference's seqlen template choice).
    """
    total, three, h, d = qkv.shape
    if three != 3:
        raise ValueError("qkv must be (total, 3, heads, head_dim)")
    b = cu_seqlens.shape[0] - 1
    lens = (cu_seqlens[1:] - cu_seqlens[:-1]).astype(jnp.int32)
    row, off = _token_coords(cu_seqlens, total)

    # scatter packed tokens into dense (b, max_s, 3, h, d); padded slots
    # stay zero and are masked by kv_seqlens inside the kernel
    dense = jnp.zeros((b, max_s) + qkv.shape[1:], qkv.dtype)
    dense = dense.at[row, off].set(qkv)
    q = dense[:, :, 0].transpose(0, 2, 1, 3)      # (b, h, s, d)
    k = dense[:, :, 1].transpose(0, 2, 1, 3)
    v = dense[:, :, 2].transpose(0, 2, 1, 3)

    seed = None
    if p_dropout > 0.0 and is_training:
        if dropout_rng is None:
            raise ValueError("p_dropout > 0 needs dropout_rng")
        seed = jax.random.randint(dropout_rng, (), 0, 2 ** 31 - 1,
                                  jnp.int32)
    else:
        p_dropout = 0.0
    ctx = flash_attention(q, k, v, causal=causal, kv_seqlens=lens,
                          dropout=p_dropout, dropout_seed=seed)

    # gather back to the packed token axis
    ctx = ctx.transpose(0, 2, 1, 3)               # (b, s, h, d)
    return ctx[row, off]


class FMHAFun:
    """Drop-in for the reference's autograd-function handle:
    ``FMHAFun.apply(qkv, cu_seqlens, seqlens, p_dropout, max_s,
    is_training)``."""

    @staticmethod
    def apply(qkv, cu_seqlens, seqlens, p_dropout, max_s,
              is_training=True, dropout_rng=None):
        del seqlens  # derivable from cu_seqlens (reference passes both)
        return fmha(qkv, cu_seqlens, max_s, p_dropout, is_training,
                    dropout_rng=dropout_rng)
