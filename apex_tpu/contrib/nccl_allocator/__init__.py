"""NCCL user-buffer allocator surface — TPU rebuild of
``apex/contrib/nccl_allocator/`` (``__init__.py`` +
``NCCLAllocator.cpp``: a ``torch.cuda.MemPool`` whose allocations are
``ncclCommRegister``-ed so collectives can use zero-copy user buffers).

There is nothing to register on TPU: XLA owns all device buffers and its
collectives already run zero-copy over ICI; the closest controllable
analogue is buffer donation (``jax.jit(..., donate_argnums=...)``),
which the framework's train steps use directly.  This module keeps the
reference's API shape as documented no-ops so ported call sites run:

    import apex_tpu.contrib.nccl_allocator as nccl_allocator
    nccl_allocator.init()
    with nccl_allocator.nccl_mem():
        buffers = [jnp.zeros(...) for _ in range(8)]
"""

from __future__ import annotations

import contextlib

__all__ = ["init", "nccl_mem", "create_nccl_mem_pool"]

_initialized = False


def init() -> None:
    """Reference ``nccl_allocator.init()``; no-op (nothing to hook)."""
    global _initialized
    _initialized = True


def create_nccl_mem_pool(symmetric: bool = False):
    """Reference returns a ``torch.cuda.MemPool``; here a token object."""
    return object()


@contextlib.contextmanager
def nccl_mem(pool=None, enabled: bool = True, device=None, group=None):
    """Reference context manager routing allocations into the registered
    pool.  On TPU allocations inside the block are ordinary XLA buffers —
    collectives are already zero-copy — so this only validates usage."""
    if not _initialized:
        raise RuntimeError(
            "nccl_allocator.init() must be called before nccl_mem() "
            "(apex parity)")
    del pool, enabled, device, group
    yield
