"""apex.contrib.optimizers parity — re-exports.

The ZeRO-style distributed fused optimizers now live at their canonical
home :mod:`apex_tpu.parallel.distributed_optimizer` (they are data-
parallelism machinery, not contrib experiments); this module keeps the
apex ``apex.contrib.optimizers`` import paths working.
"""

from __future__ import annotations

from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.optimizers.fused_lamb import FusedLAMB
from apex_tpu.parallel.distributed_optimizer import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB",
           "FusedAdam", "FusedLamb", "FP16_Optimizer"]

# Deprecated tier parity: apex/contrib/optimizers also carries the OLD
# contrib FusedAdam/FusedLAMB/FP16_Optimizer (pre-apex.optimizers
# lineage, deprecated upstream).  Re-exported from their living homes so
# recipes importing the contrib paths run.
FusedLamb = FusedLAMB                       # the contrib-era spelling


def __getattr__(name):
    if name == "FP16_Optimizer":
        from apex_tpu.fp16_utils import FP16_Optimizer
        return FP16_Optimizer
    raise AttributeError(
        f"module 'apex_tpu.contrib.optimizers' has no attribute {name!r}")
