"""Fused multi-head attention modules — TPU rebuild of
``apex/contrib/multihead_attn/`` (``self_multihead_attn.py``,
``encdec_multihead_attn.py`` + their ``*_func.py`` CUDA autograd
functions).

The CUDA path fuses strided-batched GEMMs + softmax + philox dropout into
one autograd node; here the fused core is the Pallas flash-attention
kernel (:mod:`apex_tpu.ops.flash_attention`) — memory O(s) instead of the
reference's materialized probabilities.  Layout parity with apex/torch
MHA: activations are ``(seq, batch, hidden)``.

``include_norm_add=True`` mirrors apex's ``*_norm_add`` variants: the
input is layer-normed before projection and the residual added to the
output.  Attention-probability dropout is FUSED into the flash kernel
(counter-hash keep mask regenerated in the backward — the reference's
philox-fused dropout, ``apex/contrib/csrc/multihead_attn/dropout.cuh``),
so training with dropout keeps the O(s) memory path; ``dropout > 0``
with ``is_training=True`` requires a ``dropout_rng`` key, which seeds
the mask.  Only an arbitrary ``key_padding_mask`` still needs the
materialized-probabilities reference path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_reference,
)

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]

_f32 = jnp.float32


def _init_linear(key, out_features, in_features, bias, param_dtype):
    # apex uses xavier_uniform_ on the packed projection weights
    bound = (6.0 / (in_features + out_features)) ** 0.5
    p = {"weight": jax.random.uniform(
        key, (out_features, in_features), param_dtype, -bound, bound)}
    if bias:
        p["bias"] = jnp.zeros((out_features,), param_dtype)
    return p


def _linear(p, x):
    y = x @ p["weight"].T.astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def _attend(q, k, v, heads, causal, kv_seqlens, key_padding_mask,
            dropout, dropout_rng):
    """q/k/v: (s, b, hidden) -> (s, b, hidden) via flash attention."""
    sq, b, hidden = q.shape
    sk = k.shape[0]
    d = hidden // heads
    # (s, b, h*d) -> (b, h, s, d)
    qh = q.reshape(sq, b, heads, d).transpose(1, 2, 0, 3)
    kh = k.reshape(sk, b, heads, d).transpose(1, 2, 0, 3)
    vh = v.reshape(sk, b, heads, d).transpose(1, 2, 0, 3)
    if key_padding_mask is not None:
        # arbitrary masks need materialized probabilities; the reference
        # path owns that logic (incl. kv_seqlens + fully masked rows) so
        # the two paths cannot drift
        ctx = flash_attention_reference(
            qh, kh, vh, causal=causal, kv_seqlens=kv_seqlens,
            key_padding_mask=key_padding_mask, dropout=dropout,
            dropout_rng=dropout_rng)
    else:
        seed = None
        if dropout > 0.0:
            # the key seeds the kernel's counter-hash mask; same key =>
            # same mask, so training steps should split a fresh key
            seed = jax.random.randint(dropout_rng, (), 0, 2 ** 31 - 1,
                                      jnp.int32)
        ctx = flash_attention(qh, kh, vh, causal=causal,
                              kv_seqlens=kv_seqlens, dropout=dropout,
                              dropout_seed=seed)
    return ctx.transpose(2, 0, 1, 3).reshape(sq, b, hidden)


class SelfMultiheadAttn:
    """apex ``SelfMultiheadAttn``: packed-QKV fused self attention.

    ``m = SelfMultiheadAttn(1024, 16); params = m.init_params(key)``;
    ``out = m(params, x)`` with ``x`` of shape ``(seq, batch, hidden)``.
    """

    def __init__(self, embed_dim, num_heads, dropout=0.0, bias=False,
                 include_norm_add=False, impl="fast",
                 param_dtype=jnp.float32):
        if embed_dim % num_heads:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.embed_dim = int(embed_dim)
        self.num_heads = int(num_heads)
        self.dropout = float(dropout)
        self.bias = bool(bias)
        self.include_norm_add = bool(include_norm_add)
        self.impl = impl
        self.param_dtype = param_dtype
        if include_norm_add:
            self.lyr_nrm = FusedLayerNorm(embed_dim,
                                          param_dtype=param_dtype)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        p = {"in_proj": _init_linear(k1, 3 * self.embed_dim,
                                     self.embed_dim, self.bias,
                                     self.param_dtype),
             "out_proj": _init_linear(k2, self.embed_dim, self.embed_dim,
                                      self.bias, self.param_dtype)}
        if self.include_norm_add:
            p["lyr_nrm"] = self.lyr_nrm.init_params()
        return p

    def __call__(self, params, query, key_padding_mask=None,
                 attn_mask=None, kv_seqlens=None, is_training=True,
                 dropout_rng=None):
        del attn_mask  # apex's fast path ignores it for self-attn too
        x = query
        if self.include_norm_add:
            x = self.lyr_nrm(params["lyr_nrm"], x).astype(query.dtype)
        qkv = _linear(params["in_proj"], x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        dropout = self.dropout if is_training else 0.0
        if dropout > 0.0 and dropout_rng is None:
            raise ValueError(
                "dropout > 0 with is_training=True needs dropout_rng")
        ctx = _attend(q, k, v, self.num_heads, False, kv_seqlens,
                      key_padding_mask, dropout, dropout_rng)
        out = _linear(params["out_proj"], ctx)
        if self.include_norm_add:
            out = out + query
        return out

    apply = __call__


class EncdecMultiheadAttn:
    """apex ``EncdecMultiheadAttn``: query from the decoder, packed KV
    from the encoder memory."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, bias=False,
                 include_norm_add=False, impl="fast",
                 param_dtype=jnp.float32):
        if embed_dim % num_heads:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.embed_dim = int(embed_dim)
        self.num_heads = int(num_heads)
        self.dropout = float(dropout)
        self.bias = bool(bias)
        self.include_norm_add = bool(include_norm_add)
        self.impl = impl
        self.param_dtype = param_dtype
        if include_norm_add:
            self.lyr_nrm = FusedLayerNorm(embed_dim,
                                          param_dtype=param_dtype)

    def init_params(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        p = {"q_proj": _init_linear(k1, self.embed_dim, self.embed_dim,
                                    self.bias, self.param_dtype),
             "kv_proj": _init_linear(k2, 2 * self.embed_dim,
                                     self.embed_dim, self.bias,
                                     self.param_dtype),
             "out_proj": _init_linear(k3, self.embed_dim, self.embed_dim,
                                      self.bias, self.param_dtype)}
        if self.include_norm_add:
            p["lyr_nrm"] = self.lyr_nrm.init_params()
        return p

    def __call__(self, params, query, key, key_padding_mask=None,
                 kv_seqlens=None, is_training=True, dropout_rng=None):
        x = query
        if self.include_norm_add:
            x = self.lyr_nrm(params["lyr_nrm"], x).astype(query.dtype)
        q = _linear(params["q_proj"], x)
        kv = _linear(params["kv_proj"], key)
        k, v = jnp.split(kv, 2, axis=-1)
        dropout = self.dropout if is_training else 0.0
        if dropout > 0.0 and dropout_rng is None:
            raise ValueError(
                "dropout > 0 with is_training=True needs dropout_rng")
        ctx = _attend(q, k, v, self.num_heads, False, kv_seqlens,
                      key_padding_mask, dropout, dropout_rng)
        out = _linear(params["out_proj"], ctx)
        if self.include_norm_add:
            out = out + query
        return out

    apply = __call__
