"""Transducer (RNN-T) fused ops — TPU rebuild of
``apex/contrib/transducer/`` (``transducer.py`` +
``csrc/transducer/transducer_joint_kernel.cu``,
``transducer_loss_kernel.cu``).

* ``TransducerJoint``: the f+g broadcast-add joint with optional fused
  ReLU/dropout and optional packed output (padding ``(t, u)`` pairs
  removed, as the CUDA kernel does to skip padded compute).  On TPU the
  dense add+act chain is one XLA fusion; packing is a gather/scatter
  with a static packed size (XLA needs static shapes where the CUDA
  kernel could size dynamically).
* ``TransducerLoss``: the RNN-T negative log-likelihood via the
  alpha (forward-variable) recurrence as nested ``lax.scan``s — the
  sequential t/u lattice dependency the CUDA kernel walks diagonally.
  Gradients come from JAX autodiff through the scans (the recompute/
  beta-pass trade the CUDA kernel makes is unnecessary: the lattice is
  O(T·U) floats and lives comfortably in HBM at speech shapes).

Inputs follow apex conventions: ``x`` is the joint output log-probs
``(B, T, U+1, V)`` (i.e. after ``log_softmax``), ``label`` ``(B, U)``,
per-sample lengths ``f_len``/``y_len``, ``blank_idx`` defaulting to 0.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_joint",
           "transducer_loss"]

_f32 = jnp.float32
_NEG = -1e30


def transducer_joint(f, g, f_len=None, g_len=None, pack_output=False,
                     relu=False, dropout_prob=0.0, dropout_rng=None,
                     batch_offsets=None, packed_batch=None):
    """Broadcast joint ``h[b,t,u] = f[b,t] + g[b,u]`` with optional fused
    ReLU/dropout; ``pack_output=True`` additionally flattens each
    sample's valid ``(t, u)`` rectangle to ``batch_offsets[b] + t *
    g_len[b] + u`` in a ``(packed_batch, H)`` buffer (the reference's
    packed layout)."""
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jnp.maximum(h, 0.0)
    if dropout_prob > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout needs dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_prob,
                                    h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_prob), 0.0)
    if not pack_output:
        return h
    if f_len is None or g_len is None or batch_offsets is None \
            or packed_batch is None:
        raise ValueError("pack_output needs f_len, g_len, batch_offsets "
                         "and a static packed_batch")
    b, t_max, u_max, hidden = h.shape
    tt = jnp.arange(t_max)[None, :, None]
    uu = jnp.arange(u_max)[None, None, :]
    valid = (tt < f_len[:, None, None]) & (uu < g_len[:, None, None])
    dest = batch_offsets[:, None, None] + tt * g_len[:, None, None] + uu
    dest = jnp.where(valid, dest, packed_batch)  # dropped row
    out = jnp.zeros((packed_batch + 1, hidden), h.dtype)
    out = out.at[dest.reshape(-1)].set(
        h.reshape(-1, hidden), mode="drop")
    return out[:packed_batch]


def _loss_single_lattice(x, label, f_len, y_len, blank_idx):
    """alpha recurrence for one batch element (vmapped): x (T, U1, V)."""
    t_max, u1, _ = x.shape
    blank = x[:, :, blank_idx]                              # (T, U1)
    emit = jnp.take_along_axis(
        x[:, :-1, :], label[None, :, None], axis=2)[:, :, 0]  # (T, U)
    u_ids = jnp.arange(u1)
    u_valid = u_ids <= y_len                                # alpha columns

    def u_scan_row(prev_alpha, t):
        """alpha[t, :] from alpha[t-1, :]."""
        from_blank = prev_alpha + blank[t - 1]              # (U1,)

        def u_body(carry, u):
            left = jnp.where(u > 0,
                             carry + emit[t, u - 1], _NEG)
            # carry is alpha[t, u-1]; emit at row t? NO — emit moves u at
            # fixed t: alpha[t,u] <- alpha[t,u-1] + emit(t, u-1)
            a = jnp.logaddexp(from_blank[u], left)
            a = jnp.where(u_valid[u], a, _NEG)
            return a, a

        _, row = jax.lax.scan(u_body, _NEG, jnp.arange(u1))
        return row, row

    # row 0: only emits from (0, u-1)
    def u0_body(carry, u):
        a = jnp.where(u == 0, 0.0, carry + emit[0, u - 1])
        a = jnp.where(u_valid[u], a, _NEG)
        return a, a

    _, alpha0 = jax.lax.scan(u0_body, 0.0, jnp.arange(u1))

    def t_body(prev, t):
        row, _ = u_scan_row(prev, t)
        # keep previous row where t >= f_len (frozen past the end)
        row = jnp.where(t < f_len, row, prev)
        return row, None

    alpha_last, _ = jax.lax.scan(t_body, alpha0, jnp.arange(1, t_max))
    final_blank = blank[f_len - 1, y_len]
    return -(alpha_last[y_len] + final_blank)


def transducer_loss(x, label, f_len, y_len, blank_idx=0):
    """RNN-T NLL per batch element: ``x (B, T, U+1, V)`` log-probs,
    ``label (B, U)``, lengths ``(B,)``.  Returns ``(B,)`` losses."""
    return jax.vmap(_loss_single_lattice,
                    in_axes=(0, 0, 0, 0, None))(
        x.astype(_f32), label.astype(jnp.int32),
        f_len.astype(jnp.int32), y_len.astype(jnp.int32), blank_idx)


class TransducerJoint:
    """apex ``TransducerJoint`` module surface."""

    def __init__(self, pack_output=False, relu=False, dropout=False,
                 dropout_prob=0.0, probe_mask=False):
        del probe_mask
        self.pack_output = bool(pack_output)
        self.relu = bool(relu)
        self.dropout_prob = float(dropout_prob) if dropout else 0.0

    def __call__(self, f, g, f_len=None, g_len=None, batch_offsets=None,
                 packed_batch=None, dropout_rng=None):
        return transducer_joint(
            f, g, f_len, g_len, pack_output=self.pack_output,
            relu=self.relu, dropout_prob=self.dropout_prob,
            dropout_rng=dropout_rng, batch_offsets=batch_offsets,
            packed_batch=packed_batch)

    apply = __call__


class TransducerLoss:
    """apex ``TransducerLoss`` module surface (unpacked input)."""

    def __init__(self, fuse_softmax_backward=True, opt=1,
                 packed_input=False):
        if packed_input:
            raise ValueError("packed_input is not supported; pass the "
                             "dense (B, T, U+1, V) log-probs")
        del fuse_softmax_backward, opt

    def __call__(self, x, label, f_len, y_len, blank_idx=0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)

    apply = __call__
