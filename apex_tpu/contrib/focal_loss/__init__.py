"""Fused focal loss — TPU rebuild of ``apex/contrib/focal_loss/``
(``focal_loss.py`` + ``csrc/focal_loss/focal_loss_cuda.cu``).

The reference fuses one-hot expansion, sigmoid, the focal modulation and
the normalization into one kernel for detection training (EfficientDet
lineage).  On TPU the same chain is a single XLA fusion; the public
surface mirrors ``focal_loss_cuda.forward``: integer class targets with
``-1`` meaning background (no positive class) and ``-2`` meaning ignore,
loss summed over all anchors and divided by ``num_positives_sum``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["focal_loss", "FocalLoss"]

_f32 = jnp.float32


def focal_loss(cls_output, cls_targets, num_positives_sum,
               num_real_classes=None, alpha=0.25, gamma=2.0,
               label_smoothing=0.0):
    """Sigmoid focal loss.

    ``cls_output``: ``(..., C)`` raw logits.  ``cls_targets``: ``(...)``
    int class ids in ``[0, C)``; ``-1`` = background (all-negative
    one-hot row), ``-2`` = ignored anchor (contributes nothing).
    Returns the scalar ``sum(loss) / num_positives_sum``.
    """
    num_classes = cls_output.shape[-1]
    if num_real_classes is None:
        num_real_classes = num_classes
    x = cls_output.astype(_f32)
    t = cls_targets.astype(jnp.int32)
    onehot = jax.nn.one_hot(jnp.where(t < 0, num_classes, t),
                            num_classes, dtype=_f32)
    if label_smoothing > 0.0:
        onehot = onehot * (1.0 - label_smoothing) + 0.5 * label_smoothing
    p = jax.nn.sigmoid(x)
    # standard numerically-stable BCE-with-logits
    bce = jnp.maximum(x, 0.0) - x * onehot + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * onehot + (1.0 - p) * (1.0 - onehot)
    a_t = alpha * onehot + (1.0 - alpha) * (1.0 - onehot)
    loss = a_t * (1.0 - p_t) ** gamma * bce
    # zero padded (fake) classes and ignored anchors
    if num_real_classes < num_classes:
        loss = loss * (jnp.arange(num_classes) < num_real_classes)
    loss = loss * (t != -2)[..., None]
    return jnp.sum(loss) / jnp.maximum(
        jnp.asarray(num_positives_sum, _f32), 1.0)


class FocalLoss:
    """Autograd-function surface parity (`FocalLoss.apply`)."""

    @staticmethod
    def apply(cls_output, cls_targets_at_level, num_positives_sum,
              num_real_classes, alpha, gamma, label_smoothing=0.0):
        return focal_loss(cls_output, cls_targets_at_level,
                          num_positives_sum, num_real_classes, alpha,
                          gamma, label_smoothing)
