"""Fused ResNet bottleneck + spatial parallelism — TPU rebuild of
``apex/contrib/bottleneck/`` (``bottleneck.py``, ``halo_exchangers.py``
+ ``csrc/bottleneck/bottleneck.cpp`` cudnn-frontend runtime fusion).

``Bottleneck`` is the conv1x1→conv3x3→conv1x1 block with per-conv
scale/bias (the reference folds frozen BN into scale/bias exactly like
this) and fused ReLUs; XLA fuses the conv+scale+bias+relu chains the way
cudnn-frontend's runtime fusion engine does.  Layout is NHWC (the
reference's explicit-NHWC path, its fast case).

``SpatialBottleneck`` shards the H dimension across a mesh axis: 1x1
convs are local, the 3x3 conv exchanges one halo row with each ICI
neighbor via :mod:`apex_tpu.contrib.peer_memory` (ppermute — the
reference's CUDA-IPC/NCCL halo moved to collective-permute) and then
runs VALID in H, so the math equals the serial SAME-padded conv.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.contrib.peer_memory import halo_exchange_1d

__all__ = ["Bottleneck", "SpatialBottleneck"]

_f32 = jnp.float32
_DN = ("NHWC", "HWIO", "NHWC")


def _conv(x, w, stride=1, padding="SAME"):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=_DN)


def _scale_bias_relu(x, scale, bias, relu=True):
    y = x * scale + bias
    return jnp.maximum(y, 0.0) if relu else y


class Bottleneck:
    """ResNet bottleneck: ``in_ch → bottleneck_ch (1x1) → (3x3, stride)
    → out_ch (1x1)`` + residual, frozen-BN folded into per-channel
    scale/bias (reference ctor: ``Bottleneck(in_channels,
    bottleneck_channels, out_channels, stride)``)."""

    def __init__(self, in_channels, bottleneck_channels, out_channels,
                 stride=1, param_dtype=jnp.float32):
        self.in_channels = int(in_channels)
        self.bottleneck_channels = int(bottleneck_channels)
        self.out_channels = int(out_channels)
        self.stride = int(stride)
        self.use_downsample = (stride != 1
                               or in_channels != out_channels)
        self.param_dtype = param_dtype

    def init_params(self, key):
        ks = jax.random.split(key, 4)
        ci, cb, co = (self.in_channels, self.bottleneck_channels,
                      self.out_channels)

        def conv_init(k, kh, kw, cin, cout):
            fan_in = kh * kw * cin
            return jax.random.normal(k, (kh, kw, cin, cout),
                                     self.param_dtype) * fan_in ** -0.5

        p = {
            "conv1": {"weight": conv_init(ks[0], 1, 1, ci, cb),
                      "scale": jnp.ones((cb,), _f32),
                      "bias": jnp.zeros((cb,), _f32)},
            "conv2": {"weight": conv_init(ks[1], 3, 3, cb, cb),
                      "scale": jnp.ones((cb,), _f32),
                      "bias": jnp.zeros((cb,), _f32)},
            "conv3": {"weight": conv_init(ks[2], 1, 1, cb, co),
                      "scale": jnp.ones((co,), _f32),
                      "bias": jnp.zeros((co,), _f32)},
        }
        if self.use_downsample:
            p["downsample"] = {"weight": conv_init(ks[3], 1, 1, ci, co),
                               "scale": jnp.ones((co,), _f32),
                               "bias": jnp.zeros((co,), _f32)}
        return p

    def _conv2(self, params, h):
        return _conv(h, params["conv2"]["weight"], self.stride, "SAME")

    def __call__(self, params, x):
        h = _conv(x, params["conv1"]["weight"])
        h = _scale_bias_relu(h, params["conv1"]["scale"],
                             params["conv1"]["bias"])
        h = self._conv2(params, h)
        h = _scale_bias_relu(h, params["conv2"]["scale"],
                             params["conv2"]["bias"])
        h = _conv(h, params["conv3"]["weight"])
        h = _scale_bias_relu(h, params["conv3"]["scale"],
                             params["conv3"]["bias"], relu=False)
        if self.use_downsample:
            r = _conv(x, params["downsample"]["weight"], self.stride)
            r = _scale_bias_relu(r, params["downsample"]["scale"],
                                 params["downsample"]["bias"],
                                 relu=False)
        else:
            r = x
        return jnp.maximum(h + r, 0.0)

    apply = __call__


class SpatialBottleneck(Bottleneck):
    """H-sharded bottleneck: call inside ``shard_map`` with the input's
    H axis split over ``axis_name`` (reference ``SpatialBottleneck`` with
    ``spatial_group_size = axis size``).  Requires stride 1 (the
    reference's spatial path is stride-1 segmentation/detection trunks;
    strided spatial convs would need halo-aligned offsets per rank)."""

    def __init__(self, in_channels, bottleneck_channels, out_channels,
                 stride=1, axis_name="spatial", param_dtype=jnp.float32):
        if stride != 1:
            raise ValueError("SpatialBottleneck supports stride=1")
        super().__init__(in_channels, bottleneck_channels, out_channels,
                         stride, param_dtype)
        self.axis_name = axis_name

    def _conv2(self, params, h):
        # one halo row each way over ICI, then VALID in H: identical to
        # the serial SAME conv (global edges zero-padded by ppermute)
        h = halo_exchange_1d(h, 1, self.axis_name, dim=1)
        return jax.lax.conv_general_dilated(
            h, params["conv2"]["weight"], window_strides=(1, 1),
            padding=((0, 0), (1, 1)), dimension_numbers=_DN)
