"""NHWC GroupBatchNorm — TPU rebuild of ``apex/contrib/groupbn/``
(``batch_norm.py`` + ``csrc/groupbn/batch_norm.cu``, the MLPerf-ResNet
fused BN kernels).

The reference fuses NHWC batch norm with the optional residual add and
ReLU (``BatchNorm2d_NHWC(fuse_relu=True)``, ``bn_addrelu``); its "group"
machinery spreads the stats reduction over a GPU group via CUDA IPC.  On
TPU: channels-last is native, the normalize+add+relu chain is one XLA
fusion, and the cross-device stats reduction is a ``psum`` over a mesh
axis (pass ``axis_name`` inside ``shard_map``) — the same design as
:mod:`apex_tpu.parallel.sync_batchnorm` but with the contrib surface.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["BatchNorm2d_NHWC"]

_f32 = jnp.float32


class BatchNorm2d_NHWC:
    """``(N, H, W, C)`` batch norm with optional fused residual-add and
    ReLU.  Functional state: ``params/state = m.init()``;
    ``y, new_state = m(params, state, x, z=None, training=True)``."""

    def __init__(self, num_features, eps=1e-5, momentum=0.9,
                 fuse_relu=False, bn_group=1, axis_name=None,
                 param_dtype=jnp.float32):
        self.num_features = int(num_features)
        self.eps = float(eps)
        self.momentum = float(momentum)
        self.fuse_relu = bool(fuse_relu)
        # bn_group>1 in the reference = stats over a device group; here
        # that group IS a mesh axis, so cross-device stats require one
        if bn_group > 1 and axis_name is None:
            raise ValueError(
                "bn_group>1 requires axis_name: on TPU the device group is "
                "a named mesh axis (stats are psummed over it)")
        self.axis_name = axis_name
        self.param_dtype = param_dtype

    def init_params(self):
        c = self.num_features
        return {"weight": jnp.ones((c,), self.param_dtype),
                "bias": jnp.zeros((c,), self.param_dtype)}

    def init_state(self):
        c = self.num_features
        return {"running_mean": jnp.zeros((c,), _f32),
                "running_var": jnp.ones((c,), _f32)}

    def __call__(self, params, state, x, z=None, training=True):
        xf = x.astype(_f32)
        if training:
            n = jnp.asarray(x.size // x.shape[-1], _f32)
            s = jnp.sum(xf, axis=(0, 1, 2))
            sq = jnp.sum(xf * xf, axis=(0, 1, 2))
            if self.axis_name is not None:
                s = jax.lax.psum(s, self.axis_name)
                sq = jax.lax.psum(sq, self.axis_name)
                n = jax.lax.psum(n, self.axis_name)
            mean = s / n
            var = sq / n - mean * mean
            unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
            m = self.momentum
            new_state = {
                "running_mean": m * state["running_mean"]
                + (1 - m) * mean,
                "running_var": m * state["running_var"]
                + (1 - m) * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["weight"].astype(_f32) \
            + params["bias"].astype(_f32)
        if z is not None:                 # fused bn_addrelu residual
            y = y + z.astype(_f32)
        if self.fuse_relu or z is not None:
            y = jnp.maximum(y, 0.0)
        return y.astype(x.dtype), new_state

    apply = __call__
