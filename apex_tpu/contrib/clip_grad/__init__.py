"""Fast gradient clipping — TPU rebuild of
``apex/contrib/clip_grad/clip_grad.py``.

Apex computes the global norm with one ``multi_tensor_l2norm`` launch and
rescales with one ``multi_tensor_scale``.  Same two fused passes here over
the packed buckets; functional (returns the clipped pytree and the norm).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import (multi_tensor_l2norm,
                                         multi_tensor_scale)

__all__ = ["clip_grad_norm_"]


def clip_grad_norm_(grads, max_norm: float, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """Clip the gradient pytree to global ``max_norm``.

    Returns ``(clipped_grads, total_norm)``.  ``norm_type`` 2.0 uses the
    fused kernel; other norms fall back to a jnp reduction (apex does the
    same: only L2 is multi-tensor)."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if norm_type == 2.0:
        total_norm, _, finf = multi_tensor_l2norm(leaves)
    else:
        acc = jnp.zeros((), jnp.float32)
        for g in leaves:
            acc = acc + jnp.sum(
                jnp.abs(g.astype(jnp.float32)) ** norm_type)
        total_norm = acc ** (1.0 / norm_type)
        finf = jnp.logical_not(jnp.isfinite(total_norm)).astype(jnp.float32)
    if error_if_nonfinite:
        # functional setting: surface as NaN-poisoned outputs instead of a
        # host-side raise (no sync inside jit)
        total_norm = jnp.where(finf > 0, jnp.nan, total_norm)
    clip_coef = jnp.minimum(max_norm / (total_norm + 1e-6), 1.0)
    clipped, _ = multi_tensor_scale(leaves, clip_coef)
    return jax.tree_util.tree_unflatten(treedef, clipped), total_norm
