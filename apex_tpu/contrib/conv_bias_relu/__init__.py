"""Fused Conv+Bias(+ReLU/+Mask) — TPU rebuild of
``apex/contrib/conv_bias_relu/`` (``conv_bias_relu.py`` +
``csrc/conv_bias_relu.cpp``, cudnn-frontend runtime-fused epilogues).

The reference exposes four autograd functions over cudnn graph fusion:
``ConvBiasReLU``, ``ConvBias``, ``ConvBiasMaskReLU``,
``ConvFrozenScaleBiasReLU``.  On TPU each is a single jitted chain —
XLA fuses conv+bias+relu epilogues into one kernel the same way the
cudnn frontend runtime-fusion engine does, so the fusion IS the
implementation; the functions exist so apex call sites port verbatim.
Layout is NHWC (the reference requires channels_last).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ConvBiasReLU", "ConvBias", "ConvBiasMaskReLU",
           "ConvFrozenScaleBiasReLU"]

_DN = ("NHWC", "HWIO", "NHWC")


def _conv(x, w, stride, padding):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=_DN)


def ConvBias(x, weight, bias, padding=0, stride=1):
    """conv + per-channel bias (reference ``ConvBias.apply``)."""
    return _conv(x, weight, stride, padding) + bias.astype(x.dtype)


def ConvBiasReLU(x, weight, bias, padding=0, stride=1):
    """conv + bias + relu (reference ``ConvBiasReLU.apply``)."""
    return jax.nn.relu(ConvBias(x, weight, bias, padding, stride))


def ConvBiasMaskReLU(x, weight, bias, mask, padding=0, stride=1):
    """conv + bias + elementwise mask + relu (reference
    ``ConvBiasMaskReLU.apply``; the mask is the dropout/DropBlock mask
    computed upstream)."""
    y = ConvBias(x, weight, bias, padding, stride)
    return jax.nn.relu(y * mask.astype(y.dtype))


def ConvFrozenScaleBiasReLU(x, weight, scale, bias, padding=0, stride=1):
    """conv + frozen-BN folded scale/bias + relu (reference
    ``ConvFrozenScaleBiasReLU.apply``)."""
    y = _conv(x, weight, stride, padding)
    return jax.nn.relu(y * scale.astype(y.dtype) + bias.astype(y.dtype))
