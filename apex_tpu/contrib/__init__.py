"""apex.contrib equivalent — opt-in fused extensions.

Each subpackage mirrors an apex contrib feature; all are importable without
build flags (the Pallas/XLA path needs no compilation step)."""

import importlib as _importlib

_SUBMODULES = (
    "clip_grad",
    "xentropy",
    "focal_loss",
    "group_norm",
    "groupbn",
    "index_mul_2d",
    "multihead_attn",
    "fmha",
    "layer_norm",
    "optimizers",
    "sparsity",
    "transducer",
    "bottleneck",
    "peer_memory",
    "conv_bias_relu",
    "cudnn_gbn",
    "nccl_p2p",
    "nccl_allocator",
    "gpu_direct_storage",
    "openfold_triton",
)


def __getattr__(name):
    if name in _SUBMODULES:
        try:
            return _importlib.import_module(f"apex_tpu.contrib.{name}")
        except ModuleNotFoundError as e:
            if e.name == f"apex_tpu.contrib.{name}":
                raise AttributeError(
                    f"apex_tpu.contrib submodule {name!r} is not available"
                ) from None
            raise
    raise AttributeError(f"module 'apex_tpu.contrib' has no attribute "
                         f"{name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
