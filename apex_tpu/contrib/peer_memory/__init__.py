"""Halo exchange — TPU rebuild of ``apex/contrib/peer_memory/``
(``peer_memory.py`` + ``peer_memory_cuda.cu``) and
``apex/contrib/nccl_p2p/`` (the two transports behind
``apex/contrib/bottleneck/halo_exchangers.py``).

The reference moves spatial halo rows between neighboring GPUs through
CUDA-IPC peer mappings or NCCL P2P.  On TPU neighbors are ICI neighbors
and the transport is ``lax.ppermute`` (XLA collective-permute), which is
the hardware remote-DMA path — no pool/registration machinery needed, so
``PeerMemoryPool`` reduces to the exchanger itself.

Use inside ``shard_map`` with the spatial axis sharded over
``axis_name``.  Devices at the global edges receive zeros (ppermute's
missing-source semantics), which matches zero padding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from apex_tpu.utils.collectives import axis_size as _axis_size

__all__ = ["halo_exchange_1d", "PeerHaloExchanger1d", "PeerMemoryPool"]


def halo_exchange_1d(x, halo, axis_name, dim=1):
    """Exchange ``halo`` slices of axis ``dim`` with both mesh neighbors;
    returns ``x`` extended by the received halos (zeros at the ends)."""
    n = _axis_size(axis_name)
    if n == 1:
        pad = [(0, 0)] * x.ndim
        pad[dim] = (halo, halo)
        return jnp.pad(x, pad)
    down = [(i, i + 1) for i in range(n - 1)]     # i's bottom -> i+1's top
    up = [(i + 1, i) for i in range(n - 1)]       # i's top -> i-1's bottom
    bottom = jax.lax.slice_in_dim(x, x.shape[dim] - halo, x.shape[dim],
                                  axis=dim)
    top = jax.lax.slice_in_dim(x, 0, halo, axis=dim)
    halo_top = jax.lax.ppermute(bottom, axis_name, down)
    halo_bottom = jax.lax.ppermute(top, axis_name, up)
    return jnp.concatenate([halo_top, x, halo_bottom], axis=dim)


class PeerHaloExchanger1d:
    """Surface parity with ``halo_exchangers.HaloExchangerPeer`` /
    ``HaloExchangerNCCL``: exchanger bound to a mesh axis."""

    def __init__(self, axis_name, halo=1, dim=1):
        self.axis_name = axis_name
        self.halo = int(halo)
        self.dim = int(dim)

    def __call__(self, x, halo=None):
        return halo_exchange_1d(x, self.halo if halo is None else halo,
                                self.axis_name, self.dim)


class PeerMemoryPool:
    """The reference's IPC buffer pool has no TPU analogue (ppermute is
    bufferless); kept as the factory the bottleneck surface expects."""

    def __init__(self, static_size=0, dynamic_size=0, peer_ranks=None,
                 axis_name="spatial"):
        del static_size, dynamic_size, peer_ranks
        self.axis_name = axis_name

    def exchanger(self, halo=1, dim=1):
        return PeerHaloExchanger1d(self.axis_name, halo, dim)
