"""FastLayerNorm surface — TPU rebuild of ``apex/contrib/layer_norm/``
(``layer_norm.py`` over ``csrc/layer_norm/ln_api.cpp`` + the persistent
per-hidden-size kernels).

The reference ships one hand-tuned kernel per supported hidden size
(768…65536); the TPU equivalent is the single Pallas LayerNorm in
:mod:`apex_tpu.ops.layer_norm` whose block shape adapts to the hidden
size, so ``FastLayerNorm`` is the module surface over that kernel with
the reference's constructor (and no hidden-size whitelist).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops.layer_norm import fused_layer_norm_affine

__all__ = ["FastLayerNorm", "fast_layer_norm"]


def fast_layer_norm(x, weight, bias, epsilon=1e-5):
    return fused_layer_norm_affine(x, weight, bias, eps=epsilon)


class FastLayerNorm(FusedLayerNorm):
    """apex ``contrib.layer_norm.FastLayerNorm(hidden_size, eps)``."""

    def __init__(self, hidden_size, eps=1e-5, param_dtype=jnp.float32):
        super().__init__(hidden_size, eps=eps, elementwise_affine=True,
                         param_dtype=param_dtype)
