"""Direct-storage tensor save/load (reference:
``apex/contrib/gpu_direct_storage/*.py`` + ``csrc/gpu_direct_storage/*.cpp``,
cuFile-based GPU<->disk DMA).

On TPU there is no cuFile: arrays live in HBM and reach disk through host
RAM.  The bottleneck this package removes is the *host* stage — python
pickle + single-threaded read()/write().  Tensors are written as a raw
contiguous buffer with a tiny JSON header via the native host runtime
(``apex_tpu/csrc/host_runtime.cpp``: per-thread fds, parallel
pread/pwrite), and pytrees are packed into ONE buffer with the
multi-threaded gather before a single parallel write.

Surface (the reference exposes torch.save-like ``save``/``load``):

    save(path, array_or_pytree)     load(path)
    save_numpy / load_numpy         single-array raw format
    save_pytree / load_pytree       packed multi-array format
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

from apex_tpu.utils import native

_MAGIC = b"APXT"


def _tohost(x) -> np.ndarray:
    # jax arrays (device or committed) -> host numpy without copies beyond
    # the device->host transfer itself
    return np.asarray(x)


def save_numpy(path: str, arr, threads: int = 4) -> None:
    # a stale pytree sidecar would flip load()'s format dispatch
    if os.path.exists(path + ".json"):
        os.unlink(path + ".json")
    host = _tohost(arr)
    a = np.ascontiguousarray(host)
    # record host.shape, not a.shape: ascontiguousarray promotes 0-d
    # scalars to 1-d, which would round-trip () as (1,)
    hdr = json.dumps({"dtype": a.dtype.str,
                      "shape": list(host.shape)}).encode()
    payload = np.empty((len(_MAGIC) + 4 + len(hdr) + a.nbytes,), np.uint8)
    payload[:4] = np.frombuffer(_MAGIC, np.uint8)
    payload[4:8] = np.frombuffer(struct.pack("<I", len(hdr)), np.uint8)
    payload[8:8 + len(hdr)] = np.frombuffer(hdr, np.uint8)
    payload[8 + len(hdr):] = a.view(np.uint8).reshape(-1)
    native.file_write(path, payload, threads=threads)


def load_numpy(path: str, threads: int = 4) -> np.ndarray:
    buf = native.file_read(path, threads=threads)
    if bytes(buf[:4]) != _MAGIC:
        raise ValueError(f"{path}: not an apex_tpu tensor file")
    (hlen,) = struct.unpack("<I", bytes(buf[4:8]))
    meta = json.loads(bytes(buf[8:8 + hlen]))
    data = buf[8 + hlen:]
    return data.view(np.dtype(meta["dtype"])).reshape(meta["shape"])


def save_pytree(path: str, tree, threads: int = 4) -> None:
    """One packed buffer + sidecar manifest (``path`` and ``path.json``)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    hosts = [_tohost(x) for x in leaves]
    arrs = [np.ascontiguousarray(h) for h in hosts]
    # shapes from the ORIGINAL host arrays: ascontiguousarray promotes
    # 0-d scalars to 1-d, which would round-trip () as (1,)
    manifest = {
        "treedef": str(treedef),
        "leaves": [{"dtype": a.dtype.str, "shape": list(h.shape)}
                   for a, h in zip(arrs, hosts)],
    }
    packed = native.pack(arrs)
    native.file_write(path, packed, threads=threads)
    with open(path + ".json", "w") as f:
        json.dump(manifest, f)


def load_pytree(path: str, tree_like=None, threads: int = 4):
    """Load a packed pytree; structure comes from ``tree_like`` (or a flat
    list of arrays is returned)."""
    import jax

    with open(path + ".json") as f:
        manifest = json.load(f)
    buf = native.file_read(path, threads=threads)
    arrs = []
    off = 0
    for meta in manifest["leaves"]:
        dt = np.dtype(meta["dtype"])
        n = int(np.prod(meta["shape"])) * dt.itemsize
        arrs.append(buf[off:off + n].view(dt).reshape(meta["shape"]))
        off += n
    if tree_like is None:
        return arrs
    treedef = jax.tree_util.tree_structure(tree_like)
    return jax.tree_util.tree_unflatten(treedef, arrs)


def save(path: str, obj, threads: int = 4) -> None:
    if isinstance(obj, (np.ndarray,)) or hasattr(obj, "__array__") \
            and not isinstance(obj, (list, tuple, dict)):
        save_numpy(path, obj, threads=threads)
    else:
        save_pytree(path, obj, threads=threads)


def load(path: str, tree_like=None, threads: int = 4):
    if os.path.exists(path + ".json"):
        return load_pytree(path, tree_like, threads=threads)
    return load_numpy(path, threads=threads)
