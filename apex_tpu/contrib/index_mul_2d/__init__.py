"""index_mul_2d — TPU rebuild of ``apex/contrib/index_mul_2d/``
(``index_mul_2d.py`` + ``csrc/index_mul_2d/index_mul_2d_cuda.cu``).

The reference fuses the gather and the elementwise product
``out = in1[idx] * in2`` (used by OpenFold) into one kernel with a
matching fused backward (scatter-add for ``d_in1``).  XLA emits exactly
that from the jnp expression (gather + multiply fuse; the transpose of
gather is scatter-add), so the op is the expression itself — kept as a
named function for surface parity and testability.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["index_mul_2d"]


def index_mul_2d(in1, in2, idx):
    """``in1[idx] * in2`` where ``in1`` is ``(N, D)``, ``idx`` ``(M,)``
    int rows, ``in2`` ``(M, D)``; returns ``(M, D)``."""
    if in1.ndim != 2 or in2.ndim != 2:
        raise ValueError("index_mul_2d operates on 2-D operands")
    return jnp.take(in1, idx, axis=0) * in2
