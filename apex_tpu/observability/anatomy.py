"""Step anatomy — measured critical-path attribution for MPMD steps.

:func:`~apex_tpu.mpmd.schedule.simulate` *predicts* where a pipeline
step's time goes; this module measures it.  Three layers, one data
model (the same ``Op(stage, kind, mb)`` vocabulary as
:func:`~apex_tpu.mpmd.schedule.stage_ops_1f1b`):

* :func:`reconstruct` ingests Chrome trace events — the structured
  ``mpmd_op`` / ``mpmd_xfer`` spans the engine emits under
  ``trace=True`` (or :func:`synthesize_events` fabricates from a
  simulation) — and rebuilds the measured per-stage, per-op schedule
  as a :class:`MeasuredTimeline`.

* :func:`attribute` partitions every second of every stage's
  ``[t0, t_end]`` window into exactly one of five categories::

      compute      the stage was running an op
      exposed_ici  waiting on an ICI hop whose payload existed
      exposed_dcn  waiting on a DCN hop whose payload existed
      bubble       waiting on upstream/downstream COMPUTE (the
                   schedule's pipeline bubble; includes tail drain)
      host_gap     none of the above — host dispatch, data stalls,
                   anything the op/xfer records can't explain

  The partition is a single cursor walk over boundary timestamps, so
  per-stage category sums telescope to the makespan exactly (float
  association error only — well under 1e-9 relative).

* :func:`diff_timelines` aligns the measured timeline against
  ``simulate()``'s predicted one: per-op latency ratios (normalized
  by their median, so a uniformly slow machine is NOT structural
  drift — that is the cost model's job), mis-ordered ops, ops the
  model didn't see, and bubbles the model didn't predict, folded into
  one ``drift_score`` that
  :meth:`~apex_tpu.resilience.autopilot.ParallelismAutopilot.observe_anatomy`
  consumes as an attribution-rich drift signal.

``tools/step_anatomy.py`` is the CLI; ``tools/bench_diff.py`` prints
attribution deltas for regressed legs; ``bench.py --legs anatomy``
and ``__graft_entry__._dryrun_anatomy`` gate it in CI.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

from apex_tpu.mpmd.schedule import Op

__all__ = [
    "OP_EVENT", "XFER_EVENT", "SCHEDULE_EVENT", "CATEGORIES",
    "MeasuredTimeline", "reconstruct", "attribute", "diff_timelines",
    "synthesize_events", "attribution_counter_events",
    "render_attribution_table", "render_diff",
]

# event names the engine emits and the reconstructor filters on; the
# shared vocabulary is the contract between mpmd.engine and this module
OP_EVENT = "mpmd_op"
XFER_EVENT = "mpmd_xfer"
SCHEDULE_EVENT = "mpmd_schedule"

CATEGORIES = ("compute", "exposed_ici", "exposed_dcn", "bubble",
              "host_gap")


def _op_key(stage: int, kind: str, mb: int) -> str:
    return f"s{stage}.{kind}.m{mb}"


# --------------------------------------------------------------------------
# reconstruction: trace events -> measured timeline
# --------------------------------------------------------------------------


@dataclass
class MeasuredTimeline:
    """The measured schedule of one step, rebuilt from trace events.

    ``ops`` rows are ``{stage, kind, mb, start, end, folded_fwd}``
    (seconds on the tracer clock, sorted by start); ``xfers`` rows are
    ``{src, dst, kind, mb, link_class, start, end}`` where ``kind`` is
    ``fwd``/``bwd`` for schedule edges (``mb >= 0``) and
    ``head_grad``/``embed_total`` for the tied-embedding sync
    (``mb == -1``)."""

    n_stages: int
    n_microbatches: int
    ops: List[Dict[str, object]]
    xfers: List[Dict[str, object]] = field(default_factory=list)
    schedule: Optional[str] = None
    step: Optional[int] = None
    meta: Dict[str, object] = field(default_factory=dict)

    @property
    def t0(self) -> float:
        return min(float(o["start"]) for o in self.ops)

    @property
    def t_end(self) -> float:
        ends = [float(o["end"]) for o in self.ops]
        ends.extend(float(x["end"]) for x in self.xfers)
        return max(ends)

    @property
    def makespan(self) -> float:
        return self.t_end - self.t0

    @property
    def busy(self) -> List[float]:
        b = [0.0] * self.n_stages
        for o in self.ops:
            b[int(o["stage"])] += float(o["end"]) - float(o["start"])
        return b

    def stage_ops(self, s: int) -> List[Dict[str, object]]:
        return [o for o in self.ops if int(o["stage"]) == s]

    def order(self) -> List[Op]:
        """The measured total order in the schedule's Op vocabulary."""
        return [Op(int(o["stage"]), str(o["kind"]), int(o["mb"]))
                for o in self.ops]


def _as_event_list(events) -> List[dict]:
    if isinstance(events, str):
        events = json.loads(events)
    if isinstance(events, dict):
        events = events.get("traceEvents", [])
    return [e for e in events if isinstance(e, dict)]


def reconstruct(events, *, step: Optional[int] = None
                ) -> MeasuredTimeline:
    """Rebuild the measured schedule of one step from trace events.

    ``events`` is a Chrome trace (the ``{"traceEvents": [...]}`` dict,
    a bare event list, or the JSON string of either) containing the
    engine's ``mpmd_op``/``mpmd_xfer`` spans; other events are
    ignored.  ``step`` selects which step to reconstruct when the
    trace holds several (default: the newest)."""
    evs = _as_event_list(events)
    op_evs = [e for e in evs
              if e.get("name") == OP_EVENT and e.get("ph") == "X"]
    if not op_evs:
        raise ValueError(
            f"no {OP_EVENT!r} events in trace — run the MPMD engine "
            "with trace=True (or synthesize_events) to get op records")
    steps = sorted({int(e.get("args", {}).get("step", 0))
                    for e in op_evs})
    if step is None:
        step = steps[-1]
    step = int(step)
    if step not in steps:
        raise ValueError(f"step {step} not in trace (has {steps})")

    ops: List[Dict[str, object]] = []
    seen: set = set()
    for e in op_evs:
        a = e.get("args", {})
        if int(a.get("step", 0)) != step:
            continue
        key = (int(a["stage"]), str(a["op"]), int(a["mb"]))
        if key in seen:
            raise ValueError(f"duplicate op event for {key} "
                             f"at step {step}")
        seen.add(key)
        start = float(e["ts"]) / 1e6
        ops.append({"stage": key[0], "kind": key[1], "mb": key[2],
                    "start": start,
                    "end": start + float(e.get("dur", 0.0)) / 1e6,
                    "folded_fwd": bool(a.get("folded_fwd", False))})
    ops.sort(key=lambda o: (o["start"], o["stage"]))

    xfers: List[Dict[str, object]] = []
    for e in evs:
        if e.get("name") != XFER_EVENT or e.get("ph") != "X":
            continue
        a = e.get("args", {})
        if int(a.get("step", 0)) != step:
            continue
        start = float(e["ts"]) / 1e6
        xfers.append({"src": int(a["src"]), "dst": int(a["dst"]),
                      "kind": str(a["kind"]), "mb": int(a.get("mb", -1)),
                      "link_class": str(a.get("link_class", "ici")),
                      "start": start,
                      "end": start + float(e.get("dur", 0.0)) / 1e6})
    xfers.sort(key=lambda x: x["start"])

    meta: Dict[str, object] = {}
    for e in evs:
        if e.get("name") == SCHEDULE_EVENT:
            a = dict(e.get("args", {}))
            if int(a.get("step", step)) == step or not meta:
                meta = a
    S = int(meta.get("n_stages",
                     1 + max(int(o["stage"]) for o in ops)))
    M = int(meta.get("n_microbatches",
                     1 + max(int(o["mb"]) for o in ops)))
    return MeasuredTimeline(
        n_stages=S, n_microbatches=M, ops=ops, xfers=xfers,
        schedule=meta.get("schedule"), step=step, meta=meta)


# --------------------------------------------------------------------------
# attribution: where did every second go?
# --------------------------------------------------------------------------


def _dependency(op: Dict[str, object], S: int, has_op: set
                ) -> Tuple[Optional[tuple], Optional[tuple]]:
    """The (producer op key, incoming xfer key) an op waits on.

    The xfer key is ``(dst, kind, mb)``; ``None`` means no transfer
    gates the op (first-stage fwd, or a last-stage bwd whose own fwd
    ran locally)."""
    s, kind, m = int(op["stage"]), str(op["kind"]), int(op["mb"])
    if kind == "fwd":
        if s == 0:
            return None, None
        return (s - 1, "fwd", m), (s, "fwd", m)
    if s < S - 1:
        return (s + 1, "bwd", m), (s, "bwd", m)
    # last-stage bwd: gated by its own fwd if one ran, else (the
    # engine's folded fwd+bwd) by the upstream activation arriving
    if (s, "fwd", m) in has_op and not op.get("folded_fwd"):
        return (s, "fwd", m), None
    if S >= 2:
        return (s - 1, "fwd", m), (s, "fwd", m)
    return None, None


def attribute(tl: MeasuredTimeline) -> Dict[str, object]:
    """Partition each stage's ``[t0, t_end]`` into the five
    :data:`CATEGORIES`.

    A gap before an op splits at the op's producer-end and
    transfer-end timestamps: waiting for the producer to finish is
    ``bubble``, waiting for the hop after the payload existed is
    ``exposed_<class>``, and the remainder up to the op start is
    ``host_gap``.  The tied-embedding sync transfers (``mb == -1``)
    claim their window on both endpoint stages as exposed link time;
    everything after a stage's last explained instant is ``bubble``
    (the drain).  Per-stage sums equal the makespan by construction
    (one monotone cursor from ``t0`` to ``t_end``)."""
    S = tl.n_stages
    t0, t_end = tl.t0, tl.t_end
    makespan = t_end - t0
    op_end = {(int(o["stage"]), str(o["kind"]), int(o["mb"])):
              float(o["end"]) for o in tl.ops}
    has_op = set(op_end)
    xfer_in = {(int(x["dst"]), str(x["kind"]), int(x["mb"])): x
               for x in tl.xfers if int(x["mb"]) >= 0}

    per_stage: List[Dict[str, object]] = []
    totals = {c: 0.0 for c in CATEGORIES}
    for s in range(S):
        acc = {c: 0.0 for c in CATEGORIES}
        segments: List[Dict[str, object]] = []
        cursor = t0

        def emit(t1: float, cat: str) -> None:
            nonlocal cursor
            t1 = min(max(float(t1), cursor), t_end)
            if t1 > cursor:
                acc[cat] += t1 - cursor
                segments.append({"t0": cursor, "t1": t1,
                                 "category": cat})
                cursor = t1

        for o in tl.stage_ops(s):
            start = float(o["start"])
            if start > cursor:
                dep, xin = _dependency(o, S, has_op)
                prod = op_end.get(dep) if dep is not None else None
                if prod is None:
                    emit(start, "host_gap")
                else:
                    emit(min(prod, start), "bubble")
                    x = xfer_in.get(xin) if xin is not None else None
                    if x is not None:
                        emit(min(float(x["end"]), start),
                             "exposed_" + str(x["link_class"]))
                    emit(start, "host_gap")
            emit(float(o["end"]), "compute")

        # tail: the tied-embedding sync hops this stage terminates
        # are exposed link time; the rest of the drain is bubble
        for x in tl.xfers:
            if int(x["mb"]) >= 0:
                continue
            if s not in (int(x["src"]), int(x["dst"])):
                continue
            emit(float(x["start"]), "bubble")
            emit(float(x["end"]), "exposed_" + str(x["link_class"]))
        emit(t_end, "bubble")

        row: Dict[str, object] = {"stage": s, **acc}
        row["total"] = sum(acc[c] for c in CATEGORIES)
        row["segments"] = segments
        per_stage.append(row)
        for c in CATEGORIES:
            totals[c] += acc[c]

    denom = S * makespan if makespan > 0 else 1.0
    return {
        "t0": t0, "t_end": t_end, "makespan": makespan,
        "n_stages": S,
        "per_stage": per_stage,
        "totals": totals,
        "fractions": {c: totals[c] / denom for c in CATEGORIES},
    }


# --------------------------------------------------------------------------
# differ: measured vs. predicted
# --------------------------------------------------------------------------


def _median(xs: Sequence[float]) -> float:
    ss = sorted(xs)
    n = len(ss)
    if n == 0:
        return 1.0
    mid = n // 2
    return ss[mid] if n % 2 else 0.5 * (ss[mid - 1] + ss[mid])


def diff_timelines(measured: MeasuredTimeline,
                   predicted: Dict[str, object], *,
                   fold_last_fwd: bool = False) -> Dict[str, object]:
    """Align a measured timeline against a ``simulate()`` result.

    ``predicted`` is the dict ``simulate()`` returns (``op_times`` /
    ``xfers`` / ``busy`` / ``makespan``).  ``fold_last_fwd=True``
    merges the predicted last stage's fwd into its bwd per
    microbatch — the engine's execution model, where the last stage
    runs one joint fwd+bwd program.

    Per-op ratios are measured/predicted durations; ``drift_score``
    is the max of (a) the worst median-normalized ratio deviation —
    a uniform slowdown is curve drift, the cost model's business, so
    it is divided out — (b) the worst per-stage idle fraction the
    model did NOT predict, and (c) the fraction of ops mis-ordered,
    missing, or unpredicted."""
    S = measured.n_stages
    pops: Dict[tuple, float] = {}
    p_order: List[tuple] = []
    for r in predicted["op_times"]:
        k = (int(r["stage"]), str(r["kind"]), int(r["mb"]))
        pops[k] = float(r["end"]) - float(r["start"])
        p_order.append(k)
    if fold_last_fwd:
        last = S - 1
        for m in range(measured.n_microbatches):
            fk, bk = (last, "fwd", m), (last, "bwd", m)
            if fk in pops and bk in pops:
                pops[bk] += pops.pop(fk)
        p_order = [k for k in p_order if k in pops]

    mops: Dict[tuple, float] = {}
    m_order: List[tuple] = []
    for o in measured.ops:
        k = (int(o["stage"]), str(o["kind"]), int(o["mb"]))
        mops[k] = float(o["end"]) - float(o["start"])
        m_order.append(k)

    matched = [k for k in p_order if k in mops]
    missing = [k for k in p_order if k not in mops]
    extra = [k for k in m_order if k not in pops]
    ratios: Dict[str, float] = {}
    for k in matched:
        p = pops[k]
        ratios[_op_key(*k)] = (mops[k] / p) if p > 0 else math.inf
    med = _median([r for r in ratios.values() if math.isfinite(r)])
    med = med if med > 0 else 1.0
    max_dev, worst = 0.0, None
    for key, r in ratios.items():
        dev = abs(r / med - 1.0) if math.isfinite(r) else math.inf
        if dev > max_dev:
            max_dev, worst = dev, key

    misordered: List[Dict[str, object]] = []
    for s in range(S):
        ms = [k for k in m_order if k[0] == s]
        ps = [k for k in p_order if k[0] == s]
        for i, (mk, pk) in enumerate(zip(ms, ps)):
            if mk != pk:
                misordered.append({"stage": s, "position": i,
                                   "measured": _op_key(*mk),
                                   "predicted": _op_key(*pk)})

    m_makespan = measured.makespan
    p_makespan = float(predicted["makespan"])
    p_busy = [float(b) for b in predicted["busy"]]
    if fold_last_fwd:
        # predicted busy already includes the folded fwd compute, and
        # so does the measured joint program's span — comparable as-is
        pass
    m_busy = measured.busy
    per_stage_idle: List[Dict[str, float]] = []
    unpred = 0.0
    for s in range(S):
        mi = 1.0 - (m_busy[s] / m_makespan if m_makespan > 0 else 0.0)
        pi = 1.0 - (p_busy[s] / p_makespan if p_makespan > 0 else 0.0)
        per_stage_idle.append({"stage": s, "measured": mi,
                               "predicted": pi})
        unpred = max(unpred, mi - pi)
    unpred = max(0.0, unpred)

    n = max(len(p_order), 1)
    structural = max(len(misordered), len(missing) + len(extra)) / n
    drift = max(max_dev, unpred, structural)
    return {
        "n_ops": len(p_order),
        "matched": len(matched),
        "missing": [_op_key(*k) for k in missing],
        "extra": [_op_key(*k) for k in extra],
        "ratios": ratios,
        "median_ratio": med,
        "max_ratio_deviation": max_dev,
        "worst_op": worst,
        "misordered": misordered,
        "per_stage_idle": per_stage_idle,
        "unpredicted_bubble_fraction": unpred,
        "makespan_ratio": (m_makespan / p_makespan
                           if p_makespan > 0 else math.inf),
        "drift_score": drift,
    }


# --------------------------------------------------------------------------
# synthesis: simulate() -> trace events (round-trips + deterministic CI)
# --------------------------------------------------------------------------


def synthesize_events(sim: Dict[str, object], *, n_stages: int,
                      n_microbatches: int, schedule: str = "1f1b",
                      step: int = 0, t0: float = 0.0,
                      pid: int = 0) -> List[dict]:
    """Fabricate the engine's ``mpmd_op``/``mpmd_xfer`` trace events
    from a ``simulate()`` result — what a run matching the model
    EXACTLY would have traced.  Feeds round-trip tests and the
    deterministic bench leg; ``reconstruct`` of the output rebuilds
    the simulated schedule."""
    events: List[dict] = [{
        "name": SCHEDULE_EVENT, "ph": "i", "cat": "host", "s": "t",
        "ts": t0 * 1e6, "pid": pid, "tid": 0,
        "args": {"n_stages": int(n_stages),
                 "n_microbatches": int(n_microbatches),
                 "schedule": schedule, "step": int(step),
                 "measured": False},
    }]
    for r in sim["op_times"]:
        events.append({
            "name": OP_EVENT, "ph": "X", "cat": "host",
            "ts": (t0 + float(r["start"])) * 1e6,
            "dur": (float(r["end"]) - float(r["start"])) * 1e6,
            "pid": pid, "tid": int(r["stage"]),
            "args": {"op": str(r["kind"]), "stage": int(r["stage"]),
                     "mb": int(r["mb"]), "step": int(step)},
        })
    for x in sim["xfers"]:
        events.append({
            "name": XFER_EVENT, "ph": "X", "cat": "host",
            "ts": (t0 + float(x["start"])) * 1e6,
            "dur": (float(x["end"]) - float(x["start"])) * 1e6,
            "pid": pid, "tid": int(x["src"]),
            "args": {"src": int(x["src"]), "dst": int(x["dst"]),
                     "kind": str(x["kind"]), "mb": int(x["mb"]),
                     "link_class": str(x["link_class"]),
                     "step": int(step)},
        })
    return events


# --------------------------------------------------------------------------
# rendering: Perfetto counter lanes + text tables
# --------------------------------------------------------------------------


def attribution_counter_events(attribution: Dict[str, object], *,
                               pid: int = 0) -> List[dict]:
    """Perfetto counter tracks (``ph: "C"``), one lane per stage:
    at each attribution segment boundary the active category's series
    steps to 1 and the others to 0 — merged next to the op spans the
    timeline shows WHY each gap exists."""
    events: List[dict] = []
    zero = {c: 0 for c in CATEGORIES}
    for st in attribution["per_stage"]:
        name = f"anatomy/stage{st['stage']}"
        for seg in st["segments"]:
            args = dict(zero)
            args[str(seg["category"])] = 1
            events.append({"name": name, "ph": "C", "cat": "anatomy",
                           "ts": float(seg["t0"]) * 1e6, "pid": pid,
                           "args": args})
        events.append({"name": name, "ph": "C", "cat": "anatomy",
                       "ts": float(attribution["t_end"]) * 1e6,
                       "pid": pid, "args": dict(zero)})
    return events


def render_attribution_table(attribution: Dict[str, object]) -> str:
    """The per-stage attribution as an aligned text table."""
    cols = ["stage"] + list(CATEGORIES) + ["total"]
    rows = [cols]
    for st in attribution["per_stage"]:
        rows.append([str(st["stage"])]
                    + [f"{float(st[c]):.6f}" for c in CATEGORIES]
                    + [f"{float(st['total']):.6f}"])
    tot = attribution["totals"]
    rows.append(["sum"] + [f"{float(tot[c]):.6f}" for c in CATEGORIES]
                + [f"{sum(float(tot[c]) for c in CATEGORIES):.6f}"])
    frac = attribution["fractions"]
    rows.append(["frac"] + [f"{float(frac[c]):.4f}" for c in CATEGORIES]
                + ["1.0000"])
    widths = [max(len(r[i]) for r in rows) for i in range(len(cols))]
    lines = ["  ".join(c.rjust(w) for c, w in zip(r, widths))
             for r in rows]
    lines.insert(1, "  ".join("-" * w for w in widths))
    head = (f"makespan {attribution['makespan']:.6f}s over "
            f"{attribution['n_stages']} stages")
    return head + "\n" + "\n".join(lines)


def render_diff(diff: Dict[str, object], *, top: int = 5) -> str:
    """The differ's verdict as a short text report."""
    lines = [
        f"drift_score {diff['drift_score']:.4f}  "
        f"(median ratio {diff['median_ratio']:.3f}, "
        f"makespan ratio {diff['makespan_ratio']:.3f})",
        f"ops matched {diff['matched']}/{diff['n_ops']}"
        + (f"  missing {diff['missing']}" if diff["missing"] else "")
        + (f"  extra {diff['extra']}" if diff["extra"] else ""),
    ]
    med = diff["median_ratio"]
    devs = sorted(diff["ratios"].items(),
                  key=lambda kv: -abs(kv[1] / med - 1.0))
    for key, r in devs[:top]:
        lines.append(f"  {key}: x{r:.3f} "
                     f"({(r / med - 1.0) * 100.0:+.1f}% vs median)")
    if diff["misordered"]:
        lines.append(f"misordered ops: {len(diff['misordered'])} "
                     f"(first: {diff['misordered'][0]})")
    if diff["unpredicted_bubble_fraction"] > 0:
        lines.append("unpredicted bubble fraction "
                     f"{diff['unpredicted_bubble_fraction']:.4f}")
    return "\n".join(lines)
