"""Labeled metrics registry with JSONL + Prometheus exporters.

The single sink every apex_tpu telemetry producer writes to
(:class:`~apex_tpu.utils.profiling.ServingMetrics`, the training
monitor, ``bench.py``'s per-leg results).  Three instrument kinds, the
Prometheus trio:

* :class:`Counter` — monotonically increasing (requests served,
  anomalies skipped);
* :class:`Gauge` — a value that goes both ways (tokens/s, loss scale);
* :class:`Histogram` — bucketed observations with sum/count (step
  time, TTFT).

All instruments are labeled: a metric is declared once with its label
NAMES and every sample carries a full set of label VALUES — partial or
unknown labels raise, the Prometheus contract.  Mutations are
thread-safe (one registry lock; the serving engine and an async
checkpoint writer may share a registry) and the clock is injectable so
tests drive deterministic timestamps.

Two export surfaces:

* **JSONL event stream** — every mutation appends one JSON object
  (``ts``/``event``/``name``/``labels``/``value``) to any attached
  stream, plus free-form records via :meth:`MetricsRegistry.event`
  (the training monitor's per-step records ride this).  Append-only,
  machine-tailable, and lossless: :func:`replay_jsonl` rebuilds an
  identical registry from a stream.
* **Prometheus text snapshot** — :meth:`MetricsRegistry.prometheus`
  renders the current state in the text exposition format
  (``# HELP``/``# TYPE`` + samples; histograms as cumulative
  ``_bucket{le=...}`` series with ``_sum``/``_count``) for scrape-style
  collection.
"""

from __future__ import annotations

import io
import json
import math
import re
import threading
import time
from typing import Dict, Iterable, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

# Prometheus default buckets, in seconds — right-sized for step/request
# latencies, overridable per histogram
DEFAULT_BUCKETS = (.005, .01, .025, .05, .1, .25, .5, 1.0, 2.5, 5.0, 10.0)


def _label_key(labelnames: Sequence[str], labels: dict) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"label mismatch: declared {sorted(labelnames)}, "
            f"got {sorted(labels)}")
    return tuple(str(labels[n]) for n in labelnames)


def _fmt_labels(labelnames: Sequence[str], key: Tuple[str, ...],
                extra: str = "") -> str:
    parts = [f'{n}="{_escape(v)}"' for n, v in zip(labelnames, key)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Metric:
    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labelnames: Sequence[str]):
        self._registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)

    def _record(self, key: Tuple[str, ...], value: float) -> None:
        self._registry._emit_metric(self, key, value)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, registry, name, help, labelnames):
        super().__init__(registry, name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        key = _label_key(self.labelnames, labels)
        with self._registry._lock:
            self._values[key] = self._values.get(key, 0.0) + amount
            self._record(key, amount)

    def value(self, **labels) -> float:
        return self._values.get(_label_key(self.labelnames, labels), 0.0)

    def _samples(self):
        for key, v in sorted(self._values.items()):
            yield self.name, self.labelnames, key, "", v


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, registry, name, help, labelnames):
        super().__init__(registry, name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._registry._lock:
            self._values[key] = float(value)
            self._record(key, float(value))

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._registry._lock:
            self._values[key] = self._values.get(key, 0.0) + amount
            self._record(key, self._values[key])

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        return self._values.get(_label_key(self.labelnames, labels), 0.0)

    def _samples(self):
        for key, v in sorted(self._values.items()):
            yield self.name, self.labelnames, key, "", v


class Histogram(_Metric):
    """Fixed-boundary bucketed observations.

    Memory is BOUNDED by construction: per label set the histogram
    holds ``len(buckets)+1`` counts plus a sum/total — never the raw
    samples — so a serving run observing millions of latencies stays
    O(buckets).  :meth:`percentile` interpolates quantiles from the
    bucket counts (choose boundaries that bracket the latencies you
    care about; the answer is exact only at boundaries).
    """

    kind = "histogram"

    def __init__(self, registry, name, help, labelnames,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bs
        # per label-set: [per-bucket counts..., +Inf count], sum, count
        self._counts: Dict[Tuple[str, ...], list] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        v = float(value)
        with self._registry._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.buckets) + 1))
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[key] = self._sums.get(key, 0.0) + v
            self._totals[key] = self._totals.get(key, 0) + 1
            self._record(key, v)

    def count(self, **labels) -> int:
        return self._totals.get(_label_key(self.labelnames, labels), 0)

    def sum(self, **labels) -> float:
        return self._sums.get(_label_key(self.labelnames, labels), 0.0)

    def percentile(self, q: float, **labels) -> float:
        """The q-quantile (``0 <= q <= 1``) interpolated from bucket
        counts — ``histogram_quantile`` semantics: linear within the
        selected bucket, saturating at the top finite boundary for
        observations in the overflow bucket; 0.0 with no samples."""
        key = _label_key(self.labelnames, labels)
        with self._registry._lock:
            counts = list(self._counts.get(key, ()))
            total = self._totals.get(key, 0)
        if not total:
            return 0.0
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if not c:
                continue
            if cum + c >= rank:
                if i >= len(self.buckets):
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                return lo + (self.buckets[i] - lo) \
                    * max(rank - cum, 0.0) / c
            cum += c
        return self.buckets[-1]               # pragma: no cover

    def _samples(self):
        for key in sorted(self._counts):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[key][i]
                yield (self.name + "_bucket", self.labelnames, key,
                       f'le="{_fmt_value(b)}"', cum)
            yield (self.name + "_bucket", self.labelnames, key,
                   'le="+Inf"', self._totals[key])
            yield self.name + "_sum", self.labelnames, key, "", \
                self._sums[key]
            yield self.name + "_count", self.labelnames, key, "", \
                self._totals[key]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Declare-once, label-checked metrics with streaming export.

    ``clock`` stamps JSONL events (default wall time, so streams from
    different hosts interleave meaningfully); pass a fake counter in
    tests for deterministic output.
    """

    def __init__(self, clock=time.time):
        self.clock = clock
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self._streams: list = []        # (fileobj, owned: bool)

    # -- declaration ---------------------------------------------------------

    def _declare(self, cls, name: str, help: str,
                 labelnames: Sequence[str], **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} already declared as "
                        f"{existing.kind}{existing.labelnames}")
                return existing
            m = cls(self, name, help, labelnames, **kw)
            self._metrics[name] = m
            if self._streams:
                self._write(self._declare_record(m))
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._declare(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._declare(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._declare(Histogram, name, help, labelnames,
                             buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    # -- JSONL event stream --------------------------------------------------

    def open_stream(self, path: str) -> None:
        """Append JSONL events to ``path`` (opened append-mode, owned —
        closed by :meth:`close`)."""
        self._attach(open(path, "a", encoding="utf-8"), owned=True)

    def attach_stream(self, fileobj) -> None:
        """Append JSONL events to a caller-owned file-like object."""
        self._attach(fileobj, owned=False)

    def _attach(self, fileobj, owned: bool) -> None:
        with self._lock:
            # replays reconstruct metric CONFIG (type/help/buckets) from
            # declare records, so a late-attached stream gets the
            # declarations it missed
            for name in sorted(self._metrics):
                fileobj.write(json.dumps(
                    self._declare_record(self._metrics[name]),
                    sort_keys=True) + "\n")
            self._streams.append((fileobj, owned))

    def _declare_record(self, m: _Metric) -> dict:
        rec = {"ts": self.clock(), "event": "declare", "kind": m.kind,
               "name": m.name, "help": m.help,
               "labelnames": list(m.labelnames)}
        if isinstance(m, Histogram):
            rec["buckets"] = list(m.buckets)
        return rec

    def close(self) -> None:
        for f, owned in self._streams:
            try:
                f.flush()
                if owned:
                    f.close()
            except (OSError, ValueError):
                pass
        self._streams = []

    def _write(self, record: dict) -> None:
        if not self._streams:
            return
        line = json.dumps(record, sort_keys=True) + "\n"
        for f, _ in self._streams:
            f.write(line)
            f.flush()

    def _emit_metric(self, metric: _Metric, key, value: float) -> None:
        # no attached stream -> no record, and crucially no clock() call:
        # callers may share an injected clock with the registry
        # (ServingMetrics does), and a phantom tick per mutation would
        # skew their own timing reads
        if not self._streams:
            return
        self._write({"ts": self.clock(), "event": metric.kind,
                     "name": metric.name,
                     "labels": dict(zip(metric.labelnames, key)),
                     "value": value})

    def event(self, event: str, **fields) -> None:
        """Free-form JSONL record (e.g. one ``train_step`` record per
        step from the training monitor).  ``event`` names the record
        type; ``fields`` land as top-level keys."""
        with self._lock:
            if not self._streams:
                return
            self._write({"ts": self.clock(), "event": event, **fields})

    # -- snapshots -----------------------------------------------------------

    def prometheus(self) -> str:
        """Prometheus text exposition format snapshot of every metric."""
        out = io.StringIO()
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if m.help:
                    out.write(f"# HELP {name} {m.help}\n")
                out.write(f"# TYPE {name} {m.kind}\n")
                for sname, lnames, key, extra, v in m._samples():
                    out.write(f"{sname}{_fmt_labels(lnames, key, extra)}"
                              f" {_fmt_value(v)}\n")
        return out.getvalue()

    def snapshot(self) -> dict:
        """Nested plain-dict view: name -> {kind, labels->value} (for
        histograms: labels -> {count, sum})."""
        with self._lock:
            out = {}
            for name, m in self._metrics.items():
                if isinstance(m, Histogram):
                    series = {key: {"count": m._totals[key],
                                    "sum": m._sums[key]}
                              for key in m._counts}
                else:
                    series = dict(m._values)
                out[name] = {"kind": m.kind,
                             "labelnames": m.labelnames,
                             "series": series}
            return out


def replay_jsonl(lines: Iterable[str],
                 registry: Optional[MetricsRegistry] = None
                 ) -> Tuple[MetricsRegistry, list]:
    """Rebuild a registry from a JSONL event stream.

    ``declare`` records recreate each metric with its original help
    text, label names and (for histograms) bucket boundaries; metric
    events (``counter``/``gauge``/``histogram``) are then re-applied in
    order — counters re-accumulate their deltas, gauges re-play their
    sets, histograms re-observe every sample — so the rebuilt
    registry's :meth:`~MetricsRegistry.prometheus` snapshot is
    byte-identical to the producer's.  Free-form records are returned
    as the second element for record-level consumers
    (``tools/metrics_report.py``).
    """
    reg = registry if registry is not None else MetricsRegistry()
    records = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        kind = rec.get("event")
        if kind == "declare" and rec.get("kind") in _KINDS:
            kw = {"buckets": tuple(rec["buckets"])} \
                if rec.get("kind") == "histogram" else {}
            reg._declare(_KINDS[rec["kind"]], rec["name"],
                         rec.get("help", ""),
                         tuple(rec.get("labelnames", ())), **kw)
        elif kind in _KINDS and "name" in rec:
            labels = rec.get("labels", {})
            m = getattr(reg, kind)(rec["name"],
                                   labelnames=tuple(labels))
            if kind == "counter":
                m.inc(rec["value"], **labels)
            elif kind == "gauge":
                m.set(rec["value"], **labels)
            else:
                m.observe(rec["value"], **labels)
        else:
            records.append(rec)
    return reg, records
