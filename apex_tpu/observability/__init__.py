"""apex_tpu.observability — unified telemetry for training + serving.

One registry, four surfaces:

* :mod:`~apex_tpu.observability.registry` — labeled
  Counter/Gauge/Histogram :class:`MetricsRegistry` with an append-only
  JSONL event stream and a Prometheus text-format snapshot;
* :mod:`~apex_tpu.observability.spans` — host-side span tracing
  (:func:`span`) emitting Chrome trace-event JSON (Perfetto-loadable),
  sharing names with device ``jax.named_scope`` annotations;
* :mod:`~apex_tpu.observability.train_monitor` —
  :class:`TrainingMonitor`, wrapping any train step (notably
  :class:`~apex_tpu.resilience.GuardedTrainStep`) into step-time /
  tokens-s / MFU / grad-norm / loss-scale / anomaly series;
* :mod:`~apex_tpu.observability.comms` — static per-collective byte
  accounting (:func:`collective_stats`) from compiled HLO.

The MEASURED layer on top (ISSUE 7):

* :mod:`~apex_tpu.observability.costmodel` — collective microbenchmark
  probe + fitted α–β ring :class:`CostModel` (``tools/comms_probe.py``
  is the CLI; the profile JSON feeds the auto-parallel planner);
* :mod:`~apex_tpu.observability.request_trace` —
  :class:`RequestTracer`, per-request lifecycle spans
  (queue-wait/prefill/decode) in the serving engine, with TTFT/TPOT as
  derived quantities;
* :mod:`~apex_tpu.observability.slo` — :class:`SLOMonitor`, rolling
  percentiles + declarative :class:`SLOTarget`\\ s + multi-window
  burn-rate alerts.

The FLEET layer on top (ISSUE 13):

* :mod:`~apex_tpu.observability.fleetobs` — :class:`TraceContext`
  causal propagation (router-minted, engine-stamped Chrome flow
  events that stitch a request's journey across replicas),
  :class:`FleetCollector` (N-replica clock-aligned merged timelines +
  fleet-level SLO burn), :func:`check_flows` (measured trace
  continuity), and the :class:`FlightRecorder` anomaly black box.

``tools/metrics_report.py`` renders a JSONL stream into a human
summary (``--trace`` merges it with a span trace onto one timeline);
``tools/fleet_report.py`` does the N-replica version;
``docs/source/observability.md`` is the user guide.
"""

from apex_tpu.observability.anatomy import (
    MeasuredTimeline,
    attribute,
    diff_timelines,
    reconstruct,
    synthesize_events,
)
from apex_tpu.observability.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    replay_jsonl,
)
from apex_tpu.observability.spans import Tracer, default_tracer, span
from apex_tpu.observability.train_monitor import (
    TrainingMonitor,
    calibrated_peak_flops,
)
from apex_tpu.observability.comms import (
    collective_stats,
    format_stats,
    hlo_collective_stats,
    wire_bytes,
)
from apex_tpu.observability.costmodel import (
    CostModel,
    Measurement,
    fit_cost_model,
    load_profile,
    probe_collectives,
)
from apex_tpu.observability.fleetobs import (
    FleetCollector,
    FlightRecorder,
    TraceContext,
    check_flows,
    emit_flow,
)
from apex_tpu.observability.request_trace import RequestRecord, RequestTracer
from apex_tpu.observability.slo import (
    BurnWindow,
    RollingPercentiles,
    SLOMonitor,
    SLOTarget,
)

__all__ = [
    "MeasuredTimeline",
    "attribute",
    "diff_timelines",
    "reconstruct",
    "synthesize_events",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "replay_jsonl",
    "Tracer",
    "default_tracer",
    "span",
    "TrainingMonitor",
    "calibrated_peak_flops",
    "collective_stats",
    "format_stats",
    "hlo_collective_stats",
    "wire_bytes",
    "CostModel",
    "Measurement",
    "fit_cost_model",
    "load_profile",
    "probe_collectives",
    "FleetCollector",
    "FlightRecorder",
    "TraceContext",
    "check_flows",
    "emit_flow",
    "RequestRecord",
    "RequestTracer",
    "BurnWindow",
    "RollingPercentiles",
    "SLOMonitor",
    "SLOTarget",
]
