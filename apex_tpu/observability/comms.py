"""Static per-collective byte accounting from compiled HLO.

The dp/tp/SP legs report *time*; this reports the *wire bytes* behind
it, read from the one artifact that cannot drift from reality — the
optimized HLO of the compiled program (the GSPMD-partitioned program is
where the collectives actually live, arXiv:2105.04663).  No tracing
hooks, no device work: compile (or reuse a lowered/compiled object),
scan the text, and report per-kind op counts and bytes per step.

Byte accounting per op = the LARGEST shape on the instruction (result
or operand), which matches the payload each collective moves:

* ``all-reduce``   — operand == result == the reduced tensor;
* ``all-gather``   — the gathered RESULT (shards in, full out);
* ``reduce-scatter`` — the full OPERAND (full in, shard out);
* ``collective-permute`` (ppermute) — the permuted tensor;
* ``all-to-all``   — the exchanged tensor.

Async pairs (``*-start``/``*-done``) count once, on the start.  The
returned bytes are payload bytes; actual wire traffic depends on the
algorithm (a ring all-reduce moves ~2x(k-1)/k of payload per link) —
:func:`wire_bytes` applies that standard ring model when a group size
is known.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Optional

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "s8": 1, "u2": 1, "u4": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3": 1, "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
}

# dtype[1,2,3] shape tokens anywhere in an instruction line
_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+[a-z0-9]*|pred)\[([0-9,]*)\]")

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

_OP_RE = re.compile(
    r"=\s+[^=]*?\b(" + "|".join(COLLECTIVE_KINDS)
    + r")(-start)?\(")

_GROUPS_RE = re.compile(r"replica_groups=\{?\{([0-9,]+)\}")
_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')


def _scope_of(op_name: Optional[str]) -> str:
    """``named_scope`` provenance from ``op_name`` metadata:
    ``jit(f)/jit(main)/attn/psum`` -> ``attn/psum`` (jit/pjit frames
    dropped).  Same convention as ``analysis.hlo.scope_of``."""
    if not op_name:
        return ""
    return "/".join(p for p in op_name.split("/")
                    if not (p.startswith("jit(") or p.startswith("pjit(")))


def _shape_bytes(dtype: str, dims: str) -> Optional[int]:
    width = _DTYPE_BYTES.get(dtype)
    if width is None:
        return None
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * width


def hlo_collective_stats(hlo_text: str) -> Dict[str, dict]:
    """Parse optimized HLO text into per-collective-kind accounting.

    Returns ``{kind: {"count": int, "bytes": int, "ops": [...]}}`` plus
    a ``"total"`` row.  ``bytes`` is payload bytes per single execution
    of the program; ``ops`` lists each instruction's
    ``{"bytes", "group_size", "scope"}`` — ``scope`` is the
    ``named_scope`` path from the instruction's ``op_name`` metadata, so
    a byte total traces back to the model code that issued it.
    """
    out: Dict[str, dict] = {
        k.replace("-", "_"): {"count": 0, "bytes": 0, "ops": []}
        for k in COLLECTIVE_KINDS}
    for line in hlo_text.splitlines():
        if "-done(" in line:                 # the start carries the op
            continue
        m = _OP_RE.search(line)
        if not m:
            continue
        kind = m.group(1).replace("-", "_")
        sizes = [b for dt, dims in _SHAPE_RE.findall(line)
                 for b in [_shape_bytes(dt, dims)] if b is not None]
        nbytes = max(sizes, default=0)
        g = _GROUPS_RE.search(line)
        group = len(g.group(1).split(",")) if g else None
        nm = _OP_NAME_RE.search(line)
        out[kind]["count"] += 1
        out[kind]["bytes"] += nbytes
        out[kind]["ops"].append({"bytes": nbytes, "group_size": group,
                                 "scope": _scope_of(nm.group(1)
                                                    if nm else None)})
    out["total"] = {
        "count": sum(v["count"] for v in out.values()),
        "bytes": sum(v["bytes"] for v in out.values()),
    }
    return out


def collective_stats(fn: Callable, *args, static_argnums=(),
                     **jit_kwargs) -> Dict[str, dict]:
    """Compile ``fn`` for ``args`` and account its collectives.

    ``fn`` is jitted exactly as the caller would run it (pass the same
    ``static_argnums``/jit kwargs), so the counts describe the program
    that executes — post-GSPMD partitioning and XLA's collective
    combining/reassociation, not the user-level call count.
    """
    import jax

    text = (jax.jit(fn, static_argnums=static_argnums, **jit_kwargs)
            .lower(*args).compile().as_text())
    return hlo_collective_stats(text)


def wire_bytes(stats: Dict[str, dict]) -> int:
    """Estimated bytes actually crossing links per step, under the
    standard ring algorithms: all-reduce moves ``2*(k-1)/k`` of its
    payload, all-gather/reduce-scatter ``(k-1)/k``, permute/all-to-all
    the payload itself.  Ops without a parsed group size fall back to
    the worst case (factor 2 / 1 / 1)."""
    factors = {"all_reduce": lambda k: 2 * (k - 1) / k if k else 2.0,
               "all_gather": lambda k: (k - 1) / k if k else 1.0,
               "reduce_scatter": lambda k: (k - 1) / k if k else 1.0,
               "collective_permute": lambda k: 1.0,
               "all_to_all": lambda k: 1.0}
    total = 0.0
    for kind, f in factors.items():
        for op in stats.get(kind, {}).get("ops", ()):
            total += op["bytes"] * f(op.get("group_size"))
    return int(total)


def format_stats(stats: Dict[str, dict], *,
                 by_scope: bool = False) -> str:
    """Human-readable table of a :func:`hlo_collective_stats` result.
    ``by_scope=True`` appends a per-``named_scope`` breakdown under each
    kind, attributing bytes back to the issuing model code."""
    lines = [f"{'collective':<20} {'count':>5} {'payload bytes':>14}"]
    for kind in sorted(stats):
        if kind == "total":
            continue
        row = stats[kind]
        if row["count"]:
            lines.append(f"{kind:<20} {row['count']:>5} "
                         f"{row['bytes']:>14,}")
            if by_scope:
                per: Dict[str, tuple] = {}
                for op in row.get("ops", ()):
                    s = op.get("scope") or "<no scope>"
                    c, b = per.get(s, (0, 0))
                    per[s] = (c + 1, b + op["bytes"])
                for s, (c, b) in sorted(per.items(),
                                        key=lambda kv: -kv[1][1]):
                    lines.append(f"  {s:<18} {c:>5} {b:>14,}")
    t = stats.get("total", {})
    lines.append(f"{'total':<20} {t.get('count', 0):>5} "
                 f"{t.get('bytes', 0):>14,} "
                 f"(~{wire_bytes(stats):,} wire)")
    return "\n".join(lines)
