"""Host-side span tracing -> Chrome trace-event JSON (Perfetto-loadable).

``jax.profiler`` traces the DEVICE; what it cannot see is the host-side
orchestration around it — admission loops, sampling, checkpoint
serialization, the train loop's data stalls.  :func:`span` records those
as wall-clock spans:

    with span("prefill"):
        logits, kv = prefill(params, tokens)

Spans nest per thread (a span closed out of order raises — the same
contract as ``profiling.range_push/pop``) and every span ALSO enters
``jax.named_scope`` with the same name by default, so ops traced inside
carry the name into XLA HLO metadata: the host span in the Perfetto
timeline and the device scope in xprof share one vocabulary.

Events use the Chrome trace-event format (``ph: "X"`` complete events,
microsecond timestamps, pid/tid) — ``Tracer.save(path)`` writes a file
that chrome://tracing and https://ui.perfetto.dev open directly.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional

import jax


class Tracer:
    """Collects spans into a Chrome trace-event list.  Thread-safe;
    ``clock`` is injectable (seconds; default ``time.perf_counter``)."""

    def __init__(self, clock=time.perf_counter):
        self.clock = clock
        self._lock = threading.Lock()
        self._events: list = []
        self._local = threading.local()

    # -- recording -----------------------------------------------------------

    def _stack(self) -> list:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def depth(self) -> int:
        """Current span nesting depth on THIS thread."""
        return len(self._stack())

    @contextlib.contextmanager
    def span(self, name: str, device: bool = True, **args):
        """Time a host-side region.  ``device=True`` (default) also
        enters ``jax.named_scope(name)`` so device ops traced inside
        carry the same name in HLO metadata; ``args`` become the trace
        event's ``args`` payload."""
        stack = self._stack()
        stack.append(name)
        depth = len(stack)
        t0 = self.clock()
        cm = jax.named_scope(name) if device else contextlib.nullcontext()
        error = None
        try:
            with cm:
                yield
        except BaseException as e:
            # the span still closes (and the stack still pops) when the
            # body raises; the event records what detonated so the
            # trace shows WHERE the exception path spent its time
            error = type(e).__name__
            raise
        finally:
            dt = self.clock() - t0
            popped = stack.pop()
            if popped != name:            # pragma: no cover - defensive
                raise RuntimeError(
                    f"span nesting violated: closing {name!r}, "
                    f"top of stack is {popped!r}")
            ev = {"name": name, "ph": "X", "cat": "host",
                  "ts": t0 * 1e6, "dur": dt * 1e6,
                  "pid": os.getpid(), "tid": threading.get_ident()}
            if args or depth > 1 or error:
                ev["args"] = {**args, "depth": depth}
                if error:
                    ev["args"]["error"] = error
            with self._lock:
                self._events.append(ev)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (trace-event ``ph: "i"``) — step
        boundaries, rollbacks, admissions."""
        ev = {"name": name, "ph": "i", "cat": "host", "s": "t",
              "ts": self.clock() * 1e6,
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)

    # -- async (per-flow) events ---------------------------------------------
    #
    # Host spans live on thread tracks; a REQUEST's lifecycle hops
    # threads and interleaves with other requests, so it gets a
    # nestable async track instead: Perfetto groups events sharing
    # (cat, id) onto one row per flow — one row per request.

    def async_span(self, name: str, id: object, ts: float, dur: float,
                   cat: str = "request", **args) -> None:
        """One closed async slice on flow ``(cat, id)``: a ``ph: "b"``
        / ``ph: "e"`` nestable pair at ``ts``..``ts + dur`` (seconds on
        this tracer's clock).  Emitted after the fact — the request
        tracer records raw timestamps on the hot path and materializes
        trace events once, at request completion."""
        ident = str(id)
        pid = os.getpid()
        begin = {"name": name, "ph": "b", "cat": cat, "id": ident,
                 "ts": ts * 1e6, "pid": pid, "tid": pid}
        if args:
            begin["args"] = dict(args)
        end = {"name": name, "ph": "e", "cat": cat, "id": ident,
               "ts": (ts + dur) * 1e6, "pid": pid, "tid": pid}
        with self._lock:
            self._events.append(begin)
            self._events.append(end)

    def async_instant(self, name: str, id: object, ts: float,
                      cat: str = "request", **args) -> None:
        """A point event (``ph: "n"``) on flow ``(cat, id)`` — decode
        ticks, admission edges."""
        ev = {"name": name, "ph": "n", "cat": cat, "id": str(id),
              "ts": ts * 1e6, "pid": os.getpid(), "tid": os.getpid()}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)

    # -- export --------------------------------------------------------------

    @property
    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events = []

    def to_json(self) -> str:
        """Chrome trace-event JSON (the ``traceEvents`` object form)."""
        return json.dumps({"traceEvents": self.events,
                           "displayTimeUnit": "ms"})

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json())
        return path


# module-level default tracer: `from apex_tpu.observability import span`
# is the whole integration for most call sites
_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    return _DEFAULT


def span(name: str, device: bool = True, *,
         tracer: Optional[Tracer] = None, **args):
    """``with span("prefill"): ...`` on the default tracer (or an
    explicit one via ``tracer=``)."""
    return (tracer or _DEFAULT).span(name, device=device, **args)
