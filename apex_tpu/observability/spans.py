"""Host-side span tracing -> Chrome trace-event JSON (Perfetto-loadable).

``jax.profiler`` traces the DEVICE; what it cannot see is the host-side
orchestration around it — admission loops, sampling, checkpoint
serialization, the train loop's data stalls.  :func:`span` records those
as wall-clock spans:

    with span("prefill"):
        logits, kv = prefill(params, tokens)

Spans nest per thread (a span closed out of order raises — the same
contract as ``profiling.range_push/pop``) and every span ALSO enters
``jax.named_scope`` with the same name by default, so ops traced inside
carry the name into XLA HLO metadata: the host span in the Perfetto
timeline and the device scope in xprof share one vocabulary.

Events use the Chrome trace-event format (``ph: "X"`` complete events,
microsecond timestamps, pid/tid) — ``Tracer.save(path)`` writes a file
that chrome://tracing and https://ui.perfetto.dev open directly.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from typing import Optional

import jax

# monotone instance counter: two Tracers in ONE process (an in-process
# test fleet) must still mint distinct id tags, so pid alone is not
# enough — see `Tracer.id_tag`
_INSTANCE_SEQ = itertools.count()


class Tracer:
    """Collects spans into a Chrome trace-event list.  Thread-safe;
    ``clock`` is injectable (seconds; default ``time.perf_counter``).

    ``id_tag`` namespaces this tracer's async-event ids so traces from
    several replicas merge without (cat, id) collisions: each replica's
    id counters used to restart at 0, and Perfetto folds same-id flows
    from different files onto one row.  The default tag is
    ``"<pid hex>.<instance #>"`` — unique across processes AND across
    tracers within one process.  Flow events (:meth:`flow`) are the one
    deliberate exception: their ids must MATCH across replicas (that is
    how a migrated request's fragments stitch), so they are never
    prefixed."""

    def __init__(self, clock=time.perf_counter, *,
                 id_tag: Optional[str] = None):
        self.clock = clock
        self.id_tag = (id_tag if id_tag is not None
                       else f"{os.getpid():x}.{next(_INSTANCE_SEQ)}")
        self._lock = threading.Lock()
        self._events: list = []
        self._local = threading.local()

    # -- recording -----------------------------------------------------------

    def _stack(self) -> list:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def depth(self) -> int:
        """Current span nesting depth on THIS thread."""
        return len(self._stack())

    @contextlib.contextmanager
    def span(self, name: str, device: bool = True, **args):
        """Time a host-side region.  ``device=True`` (default) also
        enters ``jax.named_scope(name)`` so device ops traced inside
        carry the same name in HLO metadata; ``args`` become the trace
        event's ``args`` payload."""
        stack = self._stack()
        stack.append(name)
        depth = len(stack)
        t0 = self.clock()
        cm = jax.named_scope(name) if device else contextlib.nullcontext()
        error = None
        try:
            with cm:
                yield
        except BaseException as e:
            # the span still closes (and the stack still pops) when the
            # body raises; the event records what detonated so the
            # trace shows WHERE the exception path spent its time
            error = type(e).__name__
            raise
        finally:
            dt = self.clock() - t0
            popped = stack.pop()
            if popped != name:            # pragma: no cover - defensive
                raise RuntimeError(
                    f"span nesting violated: closing {name!r}, "
                    f"top of stack is {popped!r}")
            ev = {"name": name, "ph": "X", "cat": "host",
                  "ts": t0 * 1e6, "dur": dt * 1e6,
                  "pid": os.getpid(), "tid": threading.get_ident()}
            if args or depth > 1 or error:
                ev["args"] = {**args, "depth": depth}
                if error:
                    ev["args"]["error"] = error
            with self._lock:
                self._events.append(ev)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (trace-event ``ph: "i"``) — step
        boundaries, rollbacks, admissions."""
        ev = {"name": name, "ph": "i", "cat": "host", "s": "t",
              "ts": self.clock() * 1e6,
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)

    # -- async (per-flow) events ---------------------------------------------
    #
    # Host spans live on thread tracks; a REQUEST's lifecycle hops
    # threads and interleaves with other requests, so it gets a
    # nestable async track instead: Perfetto groups events sharing
    # (cat, id) onto one row per flow — one row per request.

    def async_span(self, name: str, id: object, ts: float, dur: float,
                   cat: str = "request", **args) -> None:
        """One closed async slice on flow ``(cat, id)``: a ``ph: "b"``
        / ``ph: "e"`` nestable pair at ``ts``..``ts + dur`` (seconds on
        this tracer's clock).  Emitted after the fact — the request
        tracer records raw timestamps on the hot path and materializes
        trace events once, at request completion."""
        ident = f"{self.id_tag}/{id}"
        pid = os.getpid()
        begin = {"name": name, "ph": "b", "cat": cat, "id": ident,
                 "ts": ts * 1e6, "pid": pid, "tid": pid}
        if args:
            begin["args"] = dict(args)
        end = {"name": name, "ph": "e", "cat": cat, "id": ident,
               "ts": (ts + dur) * 1e6, "pid": pid, "tid": pid}
        with self._lock:
            self._events.append(begin)
            self._events.append(end)

    def async_instant(self, name: str, id: object, ts: float,
                      cat: str = "request", **args) -> None:
        """A point event (``ph: "n"``) on flow ``(cat, id)`` — decode
        ticks, admission edges."""
        ev = {"name": name, "ph": "n", "cat": cat,
              "id": f"{self.id_tag}/{id}",
              "ts": ts * 1e6, "pid": os.getpid(), "tid": os.getpid()}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)

    # -- flow events (cross-replica causality) -------------------------------
    #
    # Chrome stitches flow events sharing (cat, name, id) into one
    # arrow chain across tracks — and, after a merge, across replicas.
    # Fixed cat/name ("reqflow"/"request") keep the stitch key down to
    # the id alone; the id is the fleet-wide trace id and is therefore
    # NOT namespaced by `id_tag` (matching across replicas is the
    # point).

    FLOW_CAT = "reqflow"
    FLOW_NAME = "request"

    def flow(self, ph: str, id: object, ts: Optional[float] = None,
             **args) -> dict:
        """One flow event: ``ph`` is ``"s"`` (start), ``"t"`` (step) or
        ``"f"`` (end).  ``ts`` is seconds on this tracer's clock
        (default: now).  Returns the event dict (callers stash the span
        id they put in ``args`` to parent the next hop)."""
        if ph not in ("s", "t", "f"):
            raise ValueError(f"flow ph must be s/t/f, got {ph!r}")
        pid = os.getpid()
        ev = {"name": self.FLOW_NAME, "ph": ph, "cat": self.FLOW_CAT,
              "id": str(id),
              "ts": (self.clock() if ts is None else ts) * 1e6,
              "pid": pid, "tid": pid}
        if ph == "f":
            ev["bp"] = "e"          # bind the arrow to the enclosing slice
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)
        return ev

    # -- export --------------------------------------------------------------

    @property
    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events = []

    def to_json(self) -> str:
        """Chrome trace-event JSON (the ``traceEvents`` object form)."""
        return json.dumps({"traceEvents": self.events,
                           "displayTimeUnit": "ms"})

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json())
        return path


# module-level default tracer: `from apex_tpu.observability import span`
# is the whole integration for most call sites
_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    return _DEFAULT


def span(name: str, device: bool = True, *,
         tracer: Optional[Tracer] = None, **args):
    """``with span("prefill"): ...`` on the default tracer (or an
    explicit one via ``tracer=``)."""
    return (tracer or _DEFAULT).span(name, device=device, **args)
