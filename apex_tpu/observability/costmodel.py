"""Measured collective cost model: probe -> alpha-beta ring fits.

:mod:`~apex_tpu.observability.comms` counts the BYTES a compiled program
moves; this module predicts the TIME those bytes take on the machine we
are actually running on.  The auto-parallel planner (ROADMAP item 1)
searches thousands of (dp, tp, pp, SP, dtype) candidates — it cannot
measure each one, so its quality is bounded by the fidelity of a
measured communication profile (AMP, arXiv:2210.07297), and quantized
collectives make the curve per-dtype (EQuARX, arXiv:2506.17615).

Three pieces:

* :func:`probe_collectives` — microbenchmark ``psum`` / ``all_gather``
  / ``psum_scatter`` / ``ppermute`` across message sizes, group sizes
  and dtypes on the current mesh (hard-sync timing: 1-element
  device->host readback, min of rounds — ``block_until_ready`` can
  lie through remote-device tunnels);
* :func:`fit_cost_model` — least-squares fit of the classic ring model
  per (op, dtype, link_class): ``t = alpha * hops(k) + beta *
  wire_bytes(n, k)`` where ``hops`` is the number of serialized ring
  steps and ``wire_bytes`` the per-link traffic (the same factors
  :func:`~apex_tpu.observability.comms.wire_bytes` applies) — alpha is
  the per-hop latency, beta the inverse link bandwidth;
* :class:`CostModel` — ``predict(op, nbytes, group_size)`` in seconds,
  ``predict_stats`` over a ``collective_stats`` HLO accounting dict
  (the direct input for ``tools/autotune.py``), a ``validate`` report
  against held-out measurements, and a VERSIONED machine-profile JSON
  (:meth:`CostModel.save` / :func:`load_profile`) so a profile taken
  once per machine is reusable across runs — and refused when the
  schema moved on.

Online refits (ROADMAP item 3): a saved profile describes the machine
at probe time, and machines drift — links degrade, routes change,
neighbors appear.  :meth:`CostModel.update` buffers fresh production
measurements (collective stats, channel timings, per-request traces)
and :meth:`CostModel.refit` fits them into a refreshed model, with a
:meth:`CostModel.drift_report` comparing the new curves against the
loaded profile — the signal
:class:`~apex_tpu.resilience.autopilot.ParallelismAutopilot` debounces
before re-ranking plans.  Profiles are stamped with their probe
wall-time and measurement count (``meta["probed_at"]`` /
``meta["n_measurements"]``) so :meth:`CostModel.profile_age` /
:meth:`CostModel.is_stale` can distinguish "drifted" from "never
probed on this fleet".

Two-tier fabrics (MPMD cross-pod pipelines, ``apex_tpu.mpmd``): every
measurement and fit carries a ``link_class`` — ``"ici"`` for the
intra-pod interconnect, ``"dcn"`` for the inter-pod network — probed
as SEPARATE profiles, because one alpha-beta pair cannot describe both
a ~1us ICI hop and a ~1ms DCN hop (AMP: placement must be
heterogeneity-aware).  Profiles written before the field existed load
as ``"ici"``; :meth:`CostModel.predict_stats` accepts a per-edge
link-class map.  :func:`simulate_link_measurements` synthesizes a slow
link's curve from explicit coefficients so the two-tier fit path runs
on CPU-only CI (``tools/comms_probe.py --simulate-dcn alpha,beta``).

``tools/comms_probe.py`` is the CLI; ``__graft_entry__`` runs the
probe+fit+validate loop on the CPU mesh as a dryrun leg (held-out
predictions must land within 2x of measurement).
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
import warnings
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PROFILE_VERSION = 1

#: the collectives the probe measures, by their jax.lax names
COLLECTIVE_OPS = ("psum", "all_gather", "psum_scatter", "ppermute")

#: HLO instruction kind (comms.collective_stats keys) -> probe op.
#: all_to_all has no probe arm yet; ppermute's per-link model (factor
#: 1.0, one hop) is the closest stand-in.
HLO_KIND_TO_OP = {
    "all_reduce": "psum",
    "all_gather": "all_gather",
    "reduce_scatter": "psum_scatter",
    "collective_permute": "ppermute",
    "all_to_all": "ppermute",
}

_DTYPE_WIDTH = {"f32": 4, "bf16": 2, "f16": 2, "int8": 1, "i8": 1}


def ring_hops(op: str, group_size: int) -> float:
    """Serialized ring steps for one collective over ``group_size``
    devices: a ring all-reduce runs ``2(k-1)`` hops (reduce-scatter
    phase + all-gather phase), gather/scatter ``k-1``, a permute 1."""
    k = max(int(group_size), 1)
    if op == "psum":
        return 2.0 * (k - 1)
    if op in ("all_gather", "psum_scatter"):
        return float(k - 1)
    if op == "ppermute":
        return 1.0
    raise ValueError(f"unknown collective op {op!r}")


def ring_wire_bytes(op: str, nbytes: int, group_size: int) -> float:
    """Per-link wire traffic for ``nbytes`` of payload — the same ring
    factors as :func:`~apex_tpu.observability.comms.wire_bytes`
    (payload bytes use the comms accounting convention: the largest
    shape on the instruction)."""
    k = max(int(group_size), 1)
    if op == "psum":
        return nbytes * (2.0 * (k - 1) / k if k > 1 else 2.0)
    if op in ("all_gather", "psum_scatter"):
        return nbytes * ((k - 1) / k if k > 1 else 1.0)
    if op == "ppermute":
        return float(nbytes)
    raise ValueError(f"unknown collective op {op!r}")


@dataclasses.dataclass
class Measurement:
    """One probed point: ``time_s`` (min of rounds) for one execution
    of ``op`` moving ``nbytes`` of payload over ``group_size`` devices.
    ``nbytes`` follows the comms accounting convention so measured
    points line up with HLO-derived byte counts.  ``link_class`` names
    the fabric the point was taken on (``"ici"`` intra-pod, ``"dcn"``
    cross-pod); points from before the field existed load as ici."""
    op: str
    dtype: str
    group_size: int
    nbytes: int
    time_s: float
    link_class: str = "ici"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Measurement":
        return cls(op=d["op"], dtype=d["dtype"],
                   group_size=int(d["group_size"]),
                   nbytes=int(d["nbytes"]), time_s=float(d["time_s"]),
                   link_class=str(d.get("link_class", "ici")))


@dataclasses.dataclass
class CostFit:
    """Fitted ring coefficients for one (op, dtype) curve."""
    alpha_s: float           # per-hop latency (startup) in seconds
    beta_s_per_byte: float   # seconds per wire byte (1 / link bandwidth)
    n_points: int = 0
    max_rel_err: float = 0.0   # worst |pred/meas - 1| over the fit set

    def predict(self, op: str, nbytes: int, group_size: int) -> float:
        return (self.alpha_s * ring_hops(op, group_size)
                + self.beta_s_per_byte
                * ring_wire_bytes(op, nbytes, group_size))


def _lstsq_fit(rows: List[Tuple[float, float, float]]) -> Tuple[float, float]:
    """Least-squares ``t = alpha*h + beta*w`` with both coefficients
    clamped non-negative (a negative latency or bandwidth is noise, and
    extrapolating with one inverts the size ordering)."""
    import numpy as np

    A = np.asarray([[h, w] for h, w, _ in rows], dtype=np.float64)
    t = np.asarray([y for _, _, y in rows], dtype=np.float64)
    if len(rows) == 1:
        # single point: attribute everything to latency
        h, w, y = rows[0]
        return (y / h if h else 0.0), 0.0
    coef, *_ = np.linalg.lstsq(A, t, rcond=None)
    alpha, beta = float(coef[0]), float(coef[1])
    if beta < 0.0:            # latency-dominated noise: refit alpha only
        beta = 0.0
        hs = A[:, 0]
        alpha = float((t * hs).sum() / (hs * hs).sum()) if hs.any() else 0.0
    if alpha < 0.0:           # bandwidth-dominated: refit beta only
        alpha = 0.0
        ws = A[:, 1]
        beta = float((t * ws).sum() / (ws * ws).sum()) if ws.any() else 0.0
    return max(alpha, 0.0), max(beta, 0.0)


def fit_cost_model(measurements: Iterable[Measurement],
                   meta: Optional[dict] = None) -> "CostModel":
    """Fit one :class:`CostFit` per (op, dtype, link_class) curve by
    least squares over the ring design matrix ``[hops, wire_bytes]`` —
    ici and dcn points never mix into one fit."""
    groups: Dict[Tuple[str, str, str], List[Measurement]] = {}
    for m in measurements:
        groups.setdefault((m.op, m.dtype, m.link_class), []).append(m)
    fits: Dict[Tuple[str, str, str], CostFit] = {}
    for key, ms in groups.items():
        op = key[0]
        rows = [(ring_hops(op, m.group_size),
                 ring_wire_bytes(op, m.nbytes, m.group_size),
                 m.time_s) for m in ms]
        alpha, beta = _lstsq_fit(rows)
        fit = CostFit(alpha_s=alpha, beta_s_per_byte=beta,
                      n_points=len(ms))
        errs = [abs(fit.predict(m.op, m.nbytes, m.group_size)
                    / m.time_s - 1.0)
                for m in ms if m.time_s > 0]
        fit.max_rel_err = max(errs, default=0.0)
        fits[key] = fit
    return CostModel(fits, meta=meta)


class CostModel:
    """Per-(op, dtype, link_class) alpha-beta ring model with a
    versioned profile.

    ``predict`` never raises on an unknown dtype — it falls back to the
    op's f32 curve, then to any curve for the op (a planner asking
    about an un-probed dtype should get the conservative wider-dtype
    estimate, not an exception mid-search) — but an unknown OP raises:
    silently guessing a collective's algorithm would corrupt a plan
    comparison.  An un-probed ``link_class`` falls back to the ici
    curves the same way (the conservative choice would be the OTHER
    direction, but a planner probing dcn explicitly gets dcn curves;
    the fallback only covers profiles from before the tier existed).

    ``fits`` is the pre-link-class view — the **ici** curves keyed
    ``(op, dtype)`` — kept as the primary mutable mapping so existing
    callers and saved-profile round-trips are unchanged; construct with
    3-tuple keys ``(op, dtype, link_class)`` (or 2-tuple = ici) to
    populate other tiers, and read the full set via :meth:`curves`.
    """

    def __init__(self, fits: Dict[tuple, CostFit],
                 meta: Optional[dict] = None):
        self._by_class: Dict[str, Dict[Tuple[str, str], CostFit]] = {}
        for key, fit in dict(fits).items():
            if len(key) == 2:
                op, dtype = key
                lc = "ici"
            else:
                op, dtype, lc = key
            self._by_class.setdefault(str(lc), {})[(op, dtype)] = fit
        self._by_class.setdefault("ici", {})
        self.meta = dict(meta or {})
        # fresh production measurements buffered by update(), consumed
        # (and cleared) by a successful refit()
        self._fresh: List[Measurement] = []

    @property
    def fits(self) -> Dict[Tuple[str, str], CostFit]:
        """The ici curves keyed ``(op, dtype)`` (live view)."""
        return self._by_class["ici"]

    @property
    def link_classes(self) -> Tuple[str, ...]:
        return tuple(sorted(lc for lc, d in self._by_class.items() if d))

    def curves(self) -> Dict[Tuple[str, str, str], CostFit]:
        """Every fitted curve keyed ``(op, dtype, link_class)``."""
        return {(op, dtype, lc): fit
                for lc in sorted(self._by_class)
                for (op, dtype), fit in sorted(self._by_class[lc].items())}

    # -- staleness -----------------------------------------------------------

    def profile_age(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the profile was probed (``meta["probed_at"]``
        wall time, stamped by :meth:`save` and :meth:`refit`), or None
        for profiles that never carried the stamp."""
        probed = self.meta.get("probed_at")
        if probed is None:
            return None
        t = time.time() if now is None else now
        return max(0.0, float(t) - float(probed))

    def is_stale(self, max_age_s: float,
                 now: Optional[float] = None) -> bool:
        """True when the profile is older than ``max_age_s`` — or never
        carried a probe stamp at all ("never probed on this fleet" is
        stale by definition; "drifted" is a separate, measured signal —
        see :meth:`drift_report`)."""
        age = self.profile_age(now)
        return age is None or age > float(max_age_s)

    # -- online refits -------------------------------------------------------

    def update(self, measurements: Iterable[Measurement]) -> int:
        """Buffer fresh production measurements (collective timings from
        channels, traces, probes) for a later :meth:`refit`; returns the
        buffered count.  Cheap and non-blocking: nothing is fitted until
        refit() decides there is enough data."""
        self._fresh.extend(measurements)
        return len(self._fresh)

    @property
    def fresh_measurements(self) -> Tuple[Measurement, ...]:
        """The measurements buffered by :meth:`update` and not yet
        consumed by a successful :meth:`refit`."""
        return tuple(self._fresh)

    def drift_report(self, other: "CostModel",
                     group_size: int = 4) -> dict:
        """Relative drift of ``other``'s fitted curves vs this profile.

        Per shared (op, dtype, link_class) curve: the worst
        ``|t_other / t_self - 1|`` over a small probe grid of payload
        sizes — a pure function of the alpha-beta movement that weighs
        the coefficients the way the planner does (by predicted time),
        so a latency curve whose unused beta wiggles does not read as
        drift.  Returns ``{"curves": {key: drift}, "max_drift",
        "n_shared"}``; curves only one side fitted are skipped (no
        basis for comparison).
        """
        mine, theirs = self.curves(), other.curves()
        rows: Dict[str, float] = {}
        worst = 0.0
        for key in sorted(set(mine) & set(theirs)):
            op = key[0]
            deltas = []
            for nb in (1 << 12, 1 << 16, 1 << 20):
                t0 = mine[key].predict(op, nb, group_size)
                t1 = theirs[key].predict(op, nb, group_size)
                if t0 > 0.0:
                    deltas.append(abs(t1 / t0 - 1.0))
                elif t1 > 0.0:
                    deltas.append(math.inf)
            d = max(deltas, default=0.0)
            rows["|".join(key)] = d
            worst = max(worst, d)
        return {"curves": rows, "max_drift": worst,
                "n_shared": len(rows)}

    def refit(self, min_measurements: int = 8,
              meta: Optional[dict] = None,
              now: Optional[float] = None) -> dict:
        """Fit the buffered :meth:`update` measurements into a REFRESHED
        model and report how far it drifted from this one.

        Returns ``{"refitted", "reason", "n", "model", "drift"}``.  With
        fewer than ``min_measurements`` buffered points the refit is
        declined (``refitted=False``, buffer kept) — a handful of noisy
        samples must never move a plan.  On success the new model merges
        the freshly fitted curves over this profile's remaining ones
        (incremental update: un-remeasured tiers keep their old fits),
        carries this profile's meta re-stamped with ``probed_at`` /
        ``n_measurements``, and the buffer is cleared.  ``self`` is
        NEVER mutated: the caller — the autopilot — owns adoption of the
        refreshed model, after debouncing ``drift["max_drift"]``.
        """
        n = len(self._fresh)
        if n < int(min_measurements):
            return {"refitted": False, "n": n, "model": None,
                    "drift": None,
                    "reason": f"only {n} fresh measurement(s) "
                              f"(< {min_measurements}); keeping the "
                              "loaded profile"}
        m = dict(self.meta)
        m.update(meta or {})
        m["probed_at"] = float(time.time() if now is None else now)
        m["n_measurements"] = n
        fitted = fit_cost_model(self._fresh, meta=m)
        drift = self.drift_report(fitted)
        merged = dict(self.curves())
        merged.update(fitted.curves())
        model = CostModel(merged, meta=m)
        self._fresh = []
        return {"refitted": True, "n": n, "model": model,
                "drift": drift, "reason": ""}

    # -- prediction ----------------------------------------------------------

    def _fit_for(self, op: str, dtype: str,
                 link_class: str = "ici") -> CostFit:
        if op not in COLLECTIVE_OPS:
            raise ValueError(
                f"unknown collective op {op!r}; probed ops are "
                f"{COLLECTIVE_OPS}")
        classes = [link_class] + (["ici"] if link_class != "ici" else [])
        for lc in classes:
            d = self._by_class.get(lc, {})
            for key in ((op, dtype), (op, "f32")):
                if key in d:
                    return d[key]
            for (o, _), fit in sorted(d.items()):
                if o == op:
                    return fit
        for lc in sorted(self._by_class):
            for (o, _), fit in sorted(self._by_class[lc].items()):
                if o == op:
                    return fit
        raise KeyError(f"no fitted curve for op {op!r} "
                       f"(have {sorted(self.curves())})")

    def predict(self, op: str, nbytes: int, group_size: int,
                dtype: str = "f32", link_class: str = "ici") -> float:
        """Predicted seconds for one execution of ``op`` moving
        ``nbytes`` of payload over a ``group_size`` ring on the
        ``link_class`` fabric."""
        return self._fit_for(op, dtype, link_class).predict(
            op, nbytes, group_size)

    def predict_stats(self, stats: Dict[str, dict], group_size: int = 0,
                      dtype: str = "f32",
                      link_classes=None) -> Dict[str, dict]:
        """Predicted per-step communication time for a
        :func:`~apex_tpu.observability.comms.collective_stats` result.

        Per HLO kind: op count, payload bytes, and predicted seconds
        (ops without a parsed group size use ``group_size`` as the
        fallback ring width; 0 means "skip the latency term's hop
        count scaling" — a 2-wide ring).  ``link_classes`` picks the
        fabric per edge: a plain string prices every kind on that
        fabric, a dict maps HLO kind -> link class (unlisted kinds stay
        ici) — how the MPMD planner prices a program whose all-reduces
        stay on ICI while its collective-permutes cross pods.  Returns
        the per-kind rows plus ``{"total_s": ...}`` — the objective the
        auto-parallel planner minimizes alongside compute time.
        """
        if link_classes is None:
            link_classes = {}
        if isinstance(link_classes, str):
            link_classes = {k: link_classes for k in HLO_KIND_TO_OP}
        out: Dict[str, dict] = {}
        total = 0.0
        for kind, op in HLO_KIND_TO_OP.items():
            row = stats.get(kind)
            if not row or not row.get("count"):
                continue
            lc = str(link_classes.get(kind, "ici"))
            pred = 0.0
            for o in row.get("ops", ()):
                k = o.get("group_size") or group_size or 2
                pred += self.predict(op, o["bytes"], k, dtype=dtype,
                                     link_class=lc)
            out[kind] = {"count": row["count"], "bytes": row["bytes"],
                         "pred_s": pred, "modeled_as": op,
                         "link_class": lc}
            total += pred
        out["total_s"] = total
        return out

    # -- validation ----------------------------------------------------------

    def validate(self, measurements: Iterable[Measurement],
                 tolerance: float = 2.0) -> dict:
        """Report predicted-vs-measured ratios over ``measurements``
        (typically a held-out split the fit never saw).  A curve is
        trustworthy for planning when every ratio lands within
        ``tolerance`` (the dryrun gate uses 2x)."""
        rows = []
        for m in measurements:
            pred = self.predict(m.op, m.nbytes, m.group_size,
                                dtype=m.dtype, link_class=m.link_class)
            ratio = (pred / m.time_s if m.time_s > 0 else math.inf)
            rows.append({"op": m.op, "dtype": m.dtype,
                         "group_size": m.group_size, "nbytes": m.nbytes,
                         "link_class": m.link_class,
                         "measured_s": m.time_s, "pred_s": pred,
                         "ratio": ratio})
        ratios = [r["ratio"] for r in rows if math.isfinite(r["ratio"])]
        worst = max((max(r, 1.0 / r) for r in ratios if r > 0),
                    default=1.0)
        return {"n": len(rows), "rows": rows,
                "worst_ratio": worst,
                "within_tolerance": bool(worst <= tolerance),
                "tolerance": tolerance}

    # -- profile JSON --------------------------------------------------------

    def to_json(self) -> dict:
        # ici curves keep their pre-link-class key form ("op|dtype") so
        # older readers of a fresh profile still parse them; every entry
        # carries an explicit link_class field, and non-ici curves get a
        # third key segment to avoid collisions
        fits = {}
        for (op, dtype, lc), fit in self.curves().items():
            key = f"{op}|{dtype}" if lc == "ici" else f"{op}|{dtype}|{lc}"
            fits[key] = {
                "alpha_s": fit.alpha_s,
                "beta_s_per_byte": fit.beta_s_per_byte,
                "n_points": fit.n_points,
                "max_rel_err": fit.max_rel_err,
                "link_class": lc,
            }
        return {
            "version": PROFILE_VERSION,
            "meta": self.meta,
            "fits": fits,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "CostModel":
        ver = doc.get("version")
        if ver is None:
            # profiles written before versioning existed: still usable
            # alpha-beta data, but flag it — and is_stale() will report
            # them stale (no probed_at stamp either)
            warnings.warn(
                "machine profile carries no version field (written "
                "before profiles were versioned); loading anyway — "
                "re-run tools/comms_probe.py to refresh it",
                stacklevel=2)
        elif ver != PROFILE_VERSION:
            raise ValueError(
                f"machine profile version {ver!r} != supported "
                f"{PROFILE_VERSION}; re-run tools/comms_probe.py")
        fits = {}
        for key, f in doc.get("fits", {}).items():
            op, _, rest = key.partition("|")
            dtype, _, key_lc = rest.partition("|")
            # explicit field wins; then the key's third segment; a
            # version-current profile with neither is pre-link-class
            # data and loads as ici
            lc = str(f.get("link_class") or key_lc or "ici")
            fits[(op, dtype, lc)] = CostFit(
                alpha_s=float(f["alpha_s"]),
                beta_s_per_byte=float(f["beta_s_per_byte"]),
                n_points=int(f.get("n_points", 0)),
                max_rel_err=float(f.get("max_rel_err", 0.0)))
        return cls(fits, meta=doc.get("meta"))

    def save(self, path: str,
             measurements: Optional[Sequence[Measurement]] = None) -> str:
        """Write the machine profile (fits + meta + optionally the raw
        measurements, so a later re-fit can improve the model without
        re-probing).  Stamps staleness metadata: ``meta["probed_at"]``
        (wall time, kept if already set — a re-save does not make old
        data look fresh) and ``meta["n_measurements"]`` when the raw
        points are given."""
        self.meta.setdefault("probed_at", time.time())
        if measurements is not None:
            self.meta["n_measurements"] = len(measurements)
        doc = self.to_json()
        if measurements is not None:
            doc["measurements"] = [m.to_dict() for m in measurements]
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        return path


def load_profile(path: str) -> Tuple[CostModel, List[Measurement]]:
    """Load a saved machine profile; returns the model and whatever raw
    measurements the file carried (empty list when none)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    model = CostModel.from_json(doc)
    ms = [Measurement.from_dict(d) for d in doc.get("measurements", ())]
    return model, ms


def holdout_split(measurements: Sequence[Measurement], every: int = 3
                  ) -> Tuple[List[Measurement], List[Measurement]]:
    """(train, held_out): within each (op, dtype, link_class, group)
    curve, hold out every ``every``-th point by size rank —
    interpolation-regime validation, which is what the planner asks of
    the model."""
    curves: Dict[Tuple[str, str, str, int], List[Measurement]] = {}
    for m in measurements:
        curves.setdefault((m.op, m.dtype, m.link_class, m.group_size),
                          []).append(m)
    train: List[Measurement] = []
    held: List[Measurement] = []
    for ms in curves.values():
        ms = sorted(ms, key=lambda m: m.nbytes)
        for i, m in enumerate(ms):
            # never hold out the endpoints: they anchor the fit's range
            if 0 < i < len(ms) - 1 and i % every == 1 and len(ms) > 2:
                held.append(m)
            else:
                train.append(m)
    return train, held


# ---------------------------------------------------------------------------
# the probe
# ---------------------------------------------------------------------------

def _payload_bytes(op: str, dtype: str, n_local: int, k: int) -> int:
    """Payload bytes under the comms accounting convention (largest
    shape on the instruction): psum/psum_scatter move the per-device
    operand, all_gather's payload is the gathered RESULT, ppermute the
    permuted tensor."""
    width = _DTYPE_WIDTH[dtype]
    if op == "all_gather":
        return n_local * k * width
    return n_local * width


def probe_collectives(ops: Sequence[str] = COLLECTIVE_OPS,
                      dtypes: Sequence[str] = ("f32", "bf16", "int8"),
                      sizes: Sequence[int] = (1 << 12, 1 << 14, 1 << 16,
                                              1 << 18, 1 << 20),
                      group_sizes: Optional[Sequence[int]] = None,
                      iters: int = 4, rounds: int = 5,
                      warmup: int = 1,
                      link_class: str = "ici",
                      verbose: bool = False) -> List[Measurement]:
    """Microbenchmark the ring collectives on the current backend.

    ``link_class`` tags every measurement with the fabric being probed
    — run once per tier (on a mesh whose rings actually cross that
    fabric) to build a two-tier profile.

    ``sizes`` are PER-DEVICE local buffer bytes; each (op, dtype,
    group, size) cell is one jitted shard_map program timed with the
    hard-sync protocol (1-element device->host readback).  The cell's
    time is the MIN over ``rounds`` windows of ``iters`` calls — the
    reproducible lower bound; host scheduling noise only ever ADDS
    time, and on a 1-core host a single descheduled window would skew
    a median fit by 2x+.  Cells a backend cannot run
    (e.g. an unsupported dtype/op pairing) are skipped, not fatal — a
    partial profile is still a usable profile.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.utils.collectives import shard_map_compat
    from jax.sharding import PartitionSpec as P

    n_devices = len(jax.devices())
    if group_sizes is None:
        group_sizes = [k for k in (2, 4, 8) if n_devices % k == 0
                       and k <= n_devices]
    if not group_sizes:
        raise RuntimeError(
            f"no usable ring sizes on {n_devices} device(s); the probe "
            "needs >= 2 devices (CPU: set "
            "XLA_FLAGS=--xla_force_host_platform_device_count)")

    jnp_dtypes = {"f32": jnp.float32, "bf16": jnp.bfloat16,
                  "int8": jnp.int8}

    def sync(x):
        leaf = jax.tree_util.tree_leaves(x)[0]
        np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))
        return x

    out: List[Measurement] = []
    for k in group_sizes:
        mesh = jax.make_mesh((k,), ("probe",),
                             devices=jax.devices()[:k])
        perm = [(i, (i + 1) % k) for i in range(k)]
        body = {
            "psum": lambda x: jax.lax.psum(x, "probe"),
            "all_gather": lambda x: jax.lax.all_gather(
                x, "probe", tiled=True),
            "psum_scatter": lambda x: jax.lax.psum_scatter(
                x, "probe", tiled=True),
            "ppermute": lambda x: jax.lax.ppermute(
                x, "probe", perm=perm),
        }
        for op in ops:
            fn = jax.jit(shard_map_compat(
                body[op], mesh=mesh, in_specs=P("probe"),
                out_specs=P() if op in ("psum", "all_gather")
                else P("probe")))
            for dtype in dtypes:
                width = _DTYPE_WIDTH[dtype]
                for nbytes_local in sizes:
                    # global rows divisible by k for every op; scatter
                    # additionally splits the local rows k ways
                    n_local = max(nbytes_local // width, k)
                    n_local -= n_local % k
                    n_local = max(n_local, k)
                    x = jnp.asarray(
                        np.ones((k * n_local,), np.float32),
                        jnp_dtypes[dtype])
                    try:
                        for _ in range(warmup):
                            r = fn(x)
                        sync(r)
                        times = []
                        for _ in range(rounds):
                            t0 = time.perf_counter()
                            for _ in range(iters):
                                r = fn(x)
                            sync(r)
                            times.append(
                                (time.perf_counter() - t0) / iters)
                        t = min(times)
                    except Exception as e:     # unsupported cell
                        if verbose:
                            print(f"probe skip {op}/{dtype}/k={k}/"
                                  f"{nbytes_local}B: "
                                  f"{type(e).__name__}: {e}")
                        continue
                    m = Measurement(
                        op=op, dtype=dtype, group_size=k,
                        nbytes=_payload_bytes(op, dtype, n_local, k),
                        time_s=t, link_class=link_class)
                    out.append(m)
                    if verbose:
                        print(f"probe {op:<13} {dtype:<5} k={k} "
                              f"payload={m.nbytes:>10,}B  "
                              f"t={t * 1e6:.1f}us")
    return out


def simulate_link_measurements(
        alpha_s: float, beta_s_per_byte: float, *,
        link_class: str = "dcn",
        ops: Sequence[str] = COLLECTIVE_OPS,
        dtypes: Sequence[str] = ("f32",),
        sizes: Sequence[int] = (1 << 12, 1 << 14, 1 << 16, 1 << 18,
                                1 << 20),
        group_sizes: Sequence[int] = (2, 4),
        rel_noise: float = 0.0, seed: int = 0) -> List[Measurement]:
    """Synthesize measurements for a link that cannot be probed here.

    Times follow the ring model exactly — ``t = alpha*hops +
    beta*wire_bytes`` — so a fit over the output recovers the given
    coefficients (``rel_noise`` adds deterministic multiplicative
    jitter when a less-than-perfect curve is wanted).  This is how a
    CPU-only CI exercises the dcn tier end to end: inject a slow
    link's alpha-beta, fit, and drive the MPMD planner/simulator with
    the result (``tools/comms_probe.py --simulate-dcn alpha,beta``).
    """
    import numpy as np

    rng = np.random.RandomState(seed)
    out: List[Measurement] = []
    for op in ops:
        for dtype in dtypes:
            width = _DTYPE_WIDTH[dtype]
            for k in group_sizes:
                for nbytes_local in sizes:
                    n_local = max(nbytes_local // width, k)
                    n_local -= n_local % k
                    n_local = max(n_local, k)
                    nbytes = _payload_bytes(op, dtype, n_local, k)
                    t = (alpha_s * ring_hops(op, k)
                         + beta_s_per_byte
                         * ring_wire_bytes(op, nbytes, k))
                    if rel_noise:
                        t *= 1.0 + rel_noise * float(
                            rng.uniform(-1.0, 1.0))
                    out.append(Measurement(
                        op=op, dtype=dtype, group_size=k,
                        nbytes=nbytes, time_s=t,
                        link_class=link_class))
    return out
