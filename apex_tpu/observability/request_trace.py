"""Per-request lifecycle tracing for the continuous-batching engine.

The serving engine's latency story is per-REQUEST, not per-thread: a
request waits in the queue, gets one prefill, then shares batched decode
steps with whatever else is in flight.  :class:`RequestTracer` threads
the request id through that lifecycle —

    enqueue -> admit -> first_token -> decode ticks -> finish

— recording raw clock timestamps on the hot path (a dict write or an
int increment; no event objects, no locks of its own) and materializing
everything ONCE, at request completion:

* correlated async spans into a :class:`~apex_tpu.observability.Tracer`
  (``queue_wait`` / ``prefill`` / ``decode`` nested under one
  ``request`` slice per flow id), so a single Perfetto load shows where
  each request's latency went, interleaved with the host spans;
* the queue-wait and decode-ticks series into
  :class:`~apex_tpu.utils.profiling.ServingMetrics` — sourced from the
  trace's timestamps instead of ad-hoc ones;
* a bounded deque of :class:`RequestRecord` rows from which TTFT and
  TPOT are DERIVED quantities (``ttft = t_first - t_enqueue``,
  ``tpot = decode_s / ticks``), not separately measured ones.

The tracer is always on inside the engine; with no ``tracer=`` attached
the finish path only updates the record deque and metrics, so the
default overhead stays within the bench gate (<2% on the decode loop).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Optional

from apex_tpu.observability.fleetobs import TraceContext, emit_flow
from apex_tpu.observability.spans import Tracer


@dataclasses.dataclass
class _Live:
    t_enqueue: float
    t_admit: Optional[float] = None
    t_first: Optional[float] = None
    ticks: int = 0                 # decode ticks (tokens after the first)
    ctx: Optional[TraceContext] = None   # fleet-wide causal identity


@dataclasses.dataclass
class RequestRecord:
    """One completed request's latency attribution, all in seconds of
    the tracer's clock.  ``ttft``/``tpot`` are derived from the phase
    timestamps: ``ttft_s = queue_wait_s + prefill_s`` and ``tpot_s``
    averages the decode phase over its ticks."""
    request_id: object
    reason: str
    t_enqueue: float
    t_finish: float
    queue_wait_s: float
    prefill_s: Optional[float]     # None: never admitted
    decode_s: Optional[float]      # None: never produced a first token
    ticks: int
    error: Optional[str] = None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.prefill_s is None or self.decode_s is None:
            return None
        return self.queue_wait_s + self.prefill_s

    @property
    def tpot_s(self) -> Optional[float]:
        if self.decode_s is None or not self.ticks:
            return None
        return self.decode_s / self.ticks


class RequestTracer:
    """Lifecycle bookkeeping for in-flight requests.

    ``tracer`` (a :class:`spans.Tracer`) is optional; when given, its
    clock becomes THE clock so request slices and host spans share a
    timeline, and each finished request emits nested async trace events
    on flow id ``request_id``.  ``metrics`` (a ``ServingMetrics``)
    receives ``request_admitted(id, queue_wait)`` at admission and
    ``request_decode_ticks(id, ticks)`` at completion.  Not thread-safe
    beyond what the engine needs (all lifecycle calls happen on the
    engine's step thread).
    """

    def __init__(self, clock=time.monotonic, *,
                 tracer: Optional[Tracer] = None,
                 metrics=None, keep: int = 512):
        self.clock = tracer.clock if tracer is not None else clock
        self.tracer = tracer
        self.metrics = metrics
        self._live: dict = {}
        self.records: collections.deque = collections.deque(maxlen=keep)

    # -- lifecycle (hot path: timestamps only) -------------------------------

    def enqueue(self, request_id,
                ctx: Optional[TraceContext] = None) -> None:
        self._live[request_id] = _Live(t_enqueue=self.clock(), ctx=ctx)
        self._flow(ctx, "enqueue", request_id=request_id)

    def admit(self, request_id) -> None:
        st = self._live.get(request_id)
        if st is None:              # pragma: no cover - defensive
            return
        st.t_admit = self.clock()
        if self.metrics is not None:
            self.metrics.request_admitted(request_id,
                                          st.t_admit - st.t_enqueue)
        self._flow(st.ctx, "admit", request_id=request_id)

    def first_token(self, request_id) -> None:
        st = self._live.get(request_id)
        if st is not None:
            st.t_first = self.clock()
            self._flow(st.ctx, "first_token", request_id=request_id)

    def resumed(self, request_id) -> None:
        """A migrated/preempted request re-entered decode with prior
        progress intact — a flow step, so the cross-replica arrow
        lands on the adopting replica's lane."""
        st = self._live.get(request_id)
        if st is not None:
            self._flow(st.ctx, "resume", request_id=request_id)

    def _flow(self, ctx, phase, *, final=False, **args) -> None:
        if self.tracer is not None:
            emit_flow(self.tracer, ctx, phase, final=final, **args)

    def decode_tick(self, request_id) -> None:
        st = self._live.get(request_id)
        if st is not None:
            st.ticks += 1

    def requeue(self, request_id) -> None:
        """A preemption sent this in-flight request back to the queue.
        The live entry stays open (the request's lifecycle continues
        through re-admission — phase timestamps keep accumulating into
        the SAME record), so this only marks the event on the timeline."""
        if self.tracer is not None:
            self.tracer.instant("request_requeued", request_id=request_id)

    # fleet events (FleetRouter): marks on the timeline, same idiom as
    # requeue — the request's own lifecycle record keeps accumulating

    def retry(self, request_id, attempt: int = 0) -> None:
        """The fleet re-attempted placement after a failed or shed one."""
        if self.tracer is not None:
            self.tracer.instant("request_retry", request_id=request_id,
                                attempt=attempt)

    def migrate(self, request_id, src: int, dst: int) -> None:
        """The request moved off a dead replica onto a healthy one."""
        if self.tracer is not None:
            self.tracer.instant("request_migrated", request_id=request_id,
                                src=src, dst=dst)

    def hedge(self, request_id, replica: int) -> None:
        """A duplicate copy was dispatched for tail-latency cover."""
        if self.tracer is not None:
            self.tracer.instant("request_hedged", request_id=request_id,
                                replica=replica)

    def degrade(self, level: int) -> None:
        """The fleet's degradation ladder changed level."""
        if self.tracer is not None:
            self.tracer.instant("serving_degraded", level=level)

    @property
    def pending(self) -> int:
        """Requests enqueued but not yet finished (leak sentinel)."""
        return len(self._live)

    # -- completion: materialize spans + record ------------------------------

    def finish(self, request_id, reason: str,
               error: Optional[str] = None) -> Optional[RequestRecord]:
        st = self._live.pop(request_id, None)
        if st is None:
            return None
        now = self.clock()
        # phase boundaries; a request can die in any phase, and the
        # open phase absorbs the time up to `now` so the spans tile
        # the request slice exactly
        queue_end = st.t_admit if st.t_admit is not None else now
        prefill_s = None
        if st.t_admit is not None:
            prefill_end = st.t_first if st.t_first is not None else now
            prefill_s = prefill_end - st.t_admit
        decode_s = (now - st.t_first) if st.t_first is not None else None
        rec = RequestRecord(
            request_id=request_id, reason=reason,
            t_enqueue=st.t_enqueue, t_finish=now,
            queue_wait_s=queue_end - st.t_enqueue,
            prefill_s=prefill_s, decode_s=decode_s,
            ticks=st.ticks, error=error)
        self.records.append(rec)
        if self.metrics is not None and st.t_admit is not None:
            self.metrics.request_decode_ticks(request_id, st.ticks)
        # "migrated" is a flow STEP (the chain continues on the
        # adopting replica); every other reason terminates the flow
        self._flow(st.ctx,
                   "migrate_out" if reason == "migrated" else "finish",
                   final=reason != "migrated",
                   request_id=request_id, reason=reason)
        tr = self.tracer
        if tr is not None:
            args = {"reason": reason, "ticks": st.ticks}
            if error:
                args["error"] = error
            tr.async_span("request", request_id, st.t_enqueue,
                          now - st.t_enqueue, **args)
            tr.async_span("queue_wait", request_id, st.t_enqueue,
                          rec.queue_wait_s)
            if prefill_s is not None:
                tr.async_span("prefill", request_id, st.t_admit, prefill_s)
            if decode_s is not None:
                tr.async_span("decode", request_id, st.t_first, decode_s,
                              ticks=st.ticks)
        return rec

    # -- derived aggregates --------------------------------------------------

    def summary(self) -> dict:
        """Derived-latency percentiles over the retained records."""
        recs = list(self.records)
        ttft = [r.ttft_s for r in recs if r.ttft_s is not None]
        tpot = [r.tpot_s for r in recs if r.tpot_s is not None]
        qw = [r.queue_wait_s for r in recs]

        def pct(xs, q):
            if not xs:
                return 0.0
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]

        return {
            "requests": len(recs),
            "ttft_p50_s": pct(ttft, 0.5),
            "ttft_p95_s": pct(ttft, 0.95),
            "tpot_p50_s": pct(tpot, 0.5),
            "queue_wait_p50_s": pct(qw, 0.5),
            "queue_wait_p95_s": pct(qw, 0.95),
        }
