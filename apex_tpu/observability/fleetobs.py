"""Fleet-wide observability: causal traces, merged timelines, black box.

PR 12's fleet made a request's life MULTI-process in spirit (N replica
engines, one router, migration and hedging between them) while every
observability primitive stayed single-process: a request migrated off a
dead replica leaves two trace fragments with no shared identity, and
"fleet TTFT burn" does not exist anywhere.  This module is the missing
layer:

* :class:`TraceContext` — the causal identity a request carries across
  hops: trace id, parent span, current replica tag, hop counter.  The
  router mints it at submission, the engines' request tracers stamp
  flow events (Chrome ``ph: "s"/"t"/"f"``) against it at every
  lifecycle edge, and migration/hedging bump the hop — so a merged
  trace stitches one request's journey across replicas into a single
  Perfetto flow arrow chain.
* :func:`check_flows` — the measured version of "the trace looks
  connected": per trace id, verifies exactly one flow start, a
  terminal flow end, unbroken parent→span linkage, and (for migrated
  requests) spans from ≥ 2 replicas; also reports orphan request
  slices that no flow chain claims.
* :class:`FleetCollector` — merges N replicas' Chrome traces and JSONL
  metric streams onto one clock-aligned timeline (the N-stream
  generalization of ``tools/metrics_report.py --trace``'s two-stream
  offset rule), replays every replica's raw histogram observations
  into one fleet-level :class:`~apex_tpu.observability.slo.SLOMonitor`
  for fleet burn, and derives ``fleet_*`` rollup series.
* :class:`FlightRecorder` — a bounded per-source ring of recent spans,
  metric deltas, applied faults, and scheduler decisions that dumps a
  correlated all-sources snapshot (±window around the trigger) when
  something detonates: replica death, degradation-ladder escalation,
  training guard rollback.

Everything here is host-side pure Python over the existing trace/
registry/SLO formats — no new dependencies, fully replayable on the
virtual clock.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import time
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.observability.registry import replay_jsonl
from apex_tpu.observability.slo import SLOMonitor, SLOTarget
from apex_tpu.observability.spans import Tracer

# registry histogram -> SLOMonitor metric name, for replaying merged
# JSONL observation events into a fleet-level monitor
SERVING_SLO_METRICS = {
    "serving_ttft_seconds": "ttft",
    "serving_token_latency_seconds": "token_latency",
    "serving_queue_wait_seconds": "queue_wait",
}

DEFAULT_FLEET_TARGETS = (
    SLOTarget("ttft", 0.5, objective=0.95),
    SLOTarget("token_latency", 0.1, objective=0.99),
)

# --------------------------------------------------------------------------
# causal trace propagation
# --------------------------------------------------------------------------

@dataclasses.dataclass
class TraceContext:
    """The per-request causal identity carried across the fleet.

    Minted once (at ``Router.submit``), mutated in place as the request
    moves: every flow emission advances ``parent`` to the just-emitted
    span id, and every cross-replica transfer (migration, hedge copy)
    bumps ``hop``.  In-process fleets share the object; a real
    multi-process fleet would ship :meth:`to_dict` across the wire.
    """
    trace_id: str
    parent: str = "root"
    replica: Optional[str] = None
    hop: int = 0
    started: bool = False           # has the "s" flow event been emitted?
    seq: int = 0                    # per-context span id disambiguator

    @classmethod
    def mint(cls, request_id) -> "TraceContext":
        return cls(trace_id=f"req:{request_id}")

    def next_hop(self, replica: Optional[str] = None) -> "TraceContext":
        """Advance to the next hop (migration / hedge transfer)."""
        self.hop += 1
        self.replica = replica
        return self

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TraceContext":
        return cls(**d)


def emit_flow(tracer: Optional[Tracer], ctx: Optional[TraceContext],
              phase: str, *, final: bool = False,
              ts: Optional[float] = None, **args) -> Optional[dict]:
    """Emit one flow event for ``ctx`` on ``tracer`` and advance the
    context's parent chain.  The first emission for a context is the
    flow start (``ph: "s"``), ``final=True`` is the flow end
    (``ph: "f"``), everything between is a step (``ph: "t"``).  No-op
    (returns None) without a tracer or a context — tracing stays
    strictly opt-in on the hot path."""
    if tracer is None or ctx is None:
        return None
    span = f"{ctx.trace_id}#{ctx.hop}.{phase}.{ctx.seq}"
    ctx.seq += 1
    ph = "f" if final else ("t" if ctx.started else "s")
    ev = tracer.flow(ph, ctx.trace_id, ts, phase=phase, span=span,
                     parent=ctx.parent, hop=ctx.hop,
                     replica=tracer.id_tag, **args)
    ctx.started = True
    ctx.parent = span
    return ev


def check_flows(events: Sequence[dict], *,
                require_finish: bool = True) -> dict:
    """Verify flow-chain integrity over (merged) trace events.

    Groups flow events (``cat == "reqflow"``) by trace id and checks,
    per chain: exactly one start; at least one end when
    ``require_finish``; no event earlier than the start or later than
    the last end; and unbroken linkage — every non-start event's
    ``args.parent`` names the ``args.span`` of another event in the
    SAME chain.  Also reports orphan request slices: async ``request``
    begin events whose (replica tag, request id) no flow chain claims.

    Returns ``{"chains": {tid: info}, "complete": [...],
    "broken": {tid: [reasons]}, "orphans": [...]}`` where each chain
    info carries ``events`` / ``phases`` / ``replicas`` / ``hops``.
    """
    chains: Dict[str, List[dict]] = {}
    for ev in events:
        if ev.get("cat") == Tracer.FLOW_CAT and ev.get("ph") in "stf":
            chains.setdefault(ev["id"], []).append(ev)

    report = {"chains": {}, "complete": [], "broken": {}, "orphans": []}
    claimed: set = set()            # (replica tag, request id) pairs
    for tid, evs in sorted(chains.items()):
        evs = sorted(evs, key=lambda e: e.get("ts", 0.0))
        problems = []
        starts = [e for e in evs if e["ph"] == "s"]
        ends = [e for e in evs if e["ph"] == "f"]
        if len(starts) != 1:
            problems.append(f"{len(starts)} flow starts (want 1)")
        if require_finish and not ends:
            problems.append("no flow end")
        if starts and evs[0]["ts"] < starts[0]["ts"]:
            problems.append("event precedes the flow start")
        if ends and max(e["ts"] for e in evs) > max(e["ts"]
                                                   for e in ends):
            problems.append("event after the last flow end")
        spans = {e.get("args", {}).get("span") for e in evs}
        hops = [e.get("args", {}).get("hop", 0) for e in evs]
        for e in evs:
            a = e.get("args", {})
            if e["ph"] == "s":
                if a.get("parent") not in (None, "root"):
                    problems.append(
                        f"start parented to {a.get('parent')!r}")
            elif a.get("parent") not in spans:
                problems.append(
                    f"dangling parent {a.get('parent')!r} at phase "
                    f"{a.get('phase')!r}")
            rep, rid = a.get("replica"), a.get("request_id")
            if rep is not None and rid is not None:
                claimed.add((str(rep), str(rid)))
        info = {
            "events": len(evs),
            "phases": [e.get("args", {}).get("phase") for e in evs],
            "replicas": sorted({str(e["args"]["replica"]) for e in evs
                                if e.get("args", {}).get("replica")
                                is not None}),
            "hops": hops,
            "migrated": any(e.get("args", {}).get("phase") ==
                            "migrate_out" for e in evs),
        }
        if info["migrated"] and len(info["replicas"]) < 2:
            problems.append("migrated but spans a single replica")
        report["chains"][tid] = info
        if problems:
            report["broken"][tid] = problems
        else:
            report["complete"].append(tid)

    for ev in events:
        if (ev.get("ph") == "b" and ev.get("name") == "request"
                and ev.get("cat") == "request"):
            ident = str(ev.get("id", ""))
            tag, _, rid = ident.rpartition("/")
            if (tag, rid) not in claimed:
                report["orphans"].append(ident)
    return report


# --------------------------------------------------------------------------
# fleet aggregation
# --------------------------------------------------------------------------

def align_offset(ref_range: Optional[Tuple[float, float]],
                 other_range: Optional[Tuple[float, float]]) -> float:
    """The additive offset that aligns ``other`` onto ``ref``'s clock:
    0 when either range is empty or the ranges already overlap (shared
    clock), else min-to-min (different epochs — the 2-stream rule from
    ``tools/metrics_report.py``, reused for N streams by folding each
    stream onto the union of the already-aligned ones)."""
    if not ref_range or not other_range:
        return 0.0
    if other_range[0] > ref_range[1] or other_range[1] < ref_range[0]:
        return ref_range[0] - other_range[0]
    return 0.0


class _ReplayClock:
    """A clock that reports whatever timestamp the replay loop set —
    lets a fresh SLOMonitor relive merged history in order."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


class FleetCollector:
    """Merge N replicas' traces and metric streams into one view.

    Each :meth:`add_replica` contributes a Chrome-trace event list
    and/or a JSONL metrics stream.  :meth:`merged_timeline` emits one
    Perfetto-loadable trace with per-replica process lanes and
    clock-aligned timestamps; :meth:`fleet_burn` replays every
    replica's raw histogram observations (the JSONL streams carry each
    observation, not just cumulative state) into a single fleet-level
    :class:`SLOMonitor`; :meth:`fleet_series` rolls counters up into
    ``fleet_*`` totals; :meth:`continuity` runs :func:`check_flows`
    over the merged events.
    """

    PID_BASE = 1000

    def __init__(self):
        self._replicas: List[dict] = []

    def add_replica(self, name: str, *,
                    tracer: Optional[Tracer] = None,
                    trace_events: Optional[Sequence[dict]] = None,
                    trace_path: Optional[str] = None,
                    jsonl_lines: Optional[Sequence[str]] = None,
                    jsonl_path: Optional[str] = None) -> None:
        events: List[dict] = []
        if tracer is not None:
            events = tracer.events
        elif trace_events is not None:
            events = list(trace_events)
        elif trace_path is not None:
            with open(trace_path, encoding="utf-8") as f:
                raw = json.load(f)
            events = raw["traceEvents"] if isinstance(raw, dict) else raw
        lines: List[str] = []
        if jsonl_lines is not None:
            lines = [ln for ln in jsonl_lines if ln.strip()]
        elif jsonl_path is not None:
            with open(jsonl_path, encoding="utf-8") as f:
                lines = [ln for ln in f if ln.strip()]
        self._replicas.append({"name": name, "events": events,
                               "lines": lines})

    # -- clock alignment -----------------------------------------------------

    @staticmethod
    def _ts_range(rep: dict) -> Optional[Tuple[float, float]]:
        """This replica's timestamp range in MICROSECONDS (trace events
        are µs; JSONL ``ts`` fields are seconds and scale up)."""
        ts = [e["ts"] for e in rep["events"] if "ts" in e]
        for ln in rep["lines"]:
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if "ts" in rec:
                ts.append(rec["ts"] * 1e6)
        return (min(ts), max(ts)) if ts else None

    def offsets_us(self) -> Dict[str, float]:
        """Per-replica additive µs offsets onto the fleet timeline.
        The first replica anchors the clock; each later stream that is
        disjoint from the union of everything aligned so far is shifted
        min-to-min onto it."""
        out: Dict[str, float] = {}
        union: Optional[Tuple[float, float]] = None
        for rep in self._replicas:
            rng = self._ts_range(rep)
            off = align_offset(union, rng)
            out[rep["name"]] = off
            if rng is not None:
                lo, hi = rng[0] + off, rng[1] + off
                union = ((lo, hi) if union is None
                         else (min(union[0], lo), max(union[1], hi)))
        return out

    # -- merged outputs ------------------------------------------------------

    def events(self) -> List[dict]:
        """All replicas' trace events on the aligned clock, pid-mapped
        into per-replica lanes, sorted by timestamp."""
        offs = self.offsets_us()
        merged: List[dict] = []
        for i, rep in enumerate(self._replicas):
            off = offs[rep["name"]]
            pid = self.PID_BASE + i
            for ev in rep["events"]:
                ev = dict(ev)
                ev["pid"] = pid
                if "tid" in ev:
                    ev["tid"] = pid
                if "ts" in ev:
                    ev["ts"] = ev["ts"] + off
                merged.append(ev)
        merged.sort(key=lambda e: e.get("ts", 0.0))
        return merged

    def merged_timeline(self) -> dict:
        """One Perfetto-loadable Chrome trace: per-replica process
        lanes (``process_name`` metadata), aligned clocks, applied
        offsets recorded in the trace metadata."""
        offs = self.offsets_us()
        events: List[dict] = []
        for i, rep in enumerate(self._replicas):
            events.append({"name": "process_name", "ph": "M",
                           "pid": self.PID_BASE + i,
                           "args": {"name": f"replica:{rep['name']}"}})
        events.extend(self.events())
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": {"apex_tpu.fleet_offsets_us": offs}}

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.merged_timeline(), f)
        return path

    def merged_records(self) -> List[Tuple[float, str, dict]]:
        """All replicas' JSONL records as ``(aligned_ts_s, replica,
        record)`` in fleet-time order (declare records, which carry no
        ``ts``, are skipped)."""
        offs = self.offsets_us()
        out: List[Tuple[float, str, dict]] = []
        for rep in self._replicas:
            off_s = offs[rep["name"]] / 1e6
            for ln in rep["lines"]:
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                if "ts" in rec:
                    out.append((rec["ts"] + off_s, rep["name"], rec))
        out.sort(key=lambda r: r[0])
        return out

    # -- fleet-level SLO burn ------------------------------------------------

    def fleet_slo(self, targets: Sequence[SLOTarget] = DEFAULT_FLEET_TARGETS,
                  *, metric_map: Dict[str, str] = SERVING_SLO_METRICS,
                  registry=None, **kwargs) -> SLOMonitor:
        """Replay every replica's raw histogram observations, in merged
        fleet-time order, into one fresh :class:`SLOMonitor` — the
        fleet-aggregate burn view.  The monitor's clock is left parked
        at the last replayed timestamp so ``burn_rate`` / ``snapshot``
        read the end-of-history state."""
        clock = _ReplayClock()
        mon = SLOMonitor(targets, clock=clock, registry=registry,
                         **kwargs)
        last = 0.0
        for ts, _, rec in self.merged_records():
            metric = metric_map.get(rec.get("name", ""))
            if rec.get("event") != "histogram" or metric is None:
                continue
            clock.t = last = ts
            mon.observe(metric, rec["value"])
        clock.t = last
        return mon

    def fleet_burn(self, targets: Sequence[SLOTarget] =
                   DEFAULT_FLEET_TARGETS, *,
                   window_s: float = 300.0) -> Dict[str, float]:
        """Fleet-wide burn multiple per SLO target over the trailing
        window of merged history."""
        mon = self.fleet_slo(targets)
        return {t.name: mon.burn_rate(t, window_s) for t in mon.targets}

    # -- rollups -------------------------------------------------------------

    def fleet_series(self) -> Dict[str, float]:
        """``fleet_*`` rollups: every counter summed across replicas
        and label sets, every histogram's count and sum likewise."""
        out: Dict[str, float] = {}
        for rep in self._replicas:
            if not rep["lines"]:
                continue
            reg, _ = replay_jsonl(rep["lines"])
            for name, info in reg.snapshot().items():
                for val in info["series"].values():
                    if isinstance(val, dict):       # histogram
                        for k in ("count", "sum"):
                            key = f"fleet_{name}_{k}"
                            out[key] = out.get(key, 0.0) + val[k]
                    else:
                        key = f"fleet_{name}"
                        out[key] = out.get(key, 0.0) + val
        return out

    def replica_table(self) -> List[dict]:
        """Per-replica health/burn/occupancy rows for the fleet
        report."""
        rows = []
        for rep in self._replicas:
            row = {"replica": rep["name"],
                   "span_events": len(rep["events"]),
                   "requests": 0, "occupancy": None, "burn": {},
                   "health": None}
            if rep["lines"]:
                reg, records = replay_jsonl(rep["lines"])
                snap = reg.snapshot()
                h = snap.get("serving_requests_total", {})
                row["requests"] = int(sum(
                    v for v in h.get("series", {}).values()
                    if not isinstance(v, dict)))
                occ = snap.get("serving_slot_occupancy", {})
                vals = [v for v in occ.get("series", {}).values()
                        if not isinstance(v, dict)]
                if vals:
                    row["occupancy"] = vals[-1]
                sub = FleetCollector()
                sub.add_replica(rep["name"], trace_events=rep["events"],
                                jsonl_lines=rep["lines"])
                row["burn"] = sub.fleet_burn()
                for _, _, rec in reversed(sub.merged_records()):
                    if rec.get("event") == "replica_health":
                        row["health"] = rec.get("state")
                        break
            rows.append(row)
        return rows

    def continuity(self, **kwargs) -> dict:
        """:func:`check_flows` over the merged timeline."""
        return check_flows(self.events(), **kwargs)


# --------------------------------------------------------------------------
# anomaly flight recorder
# --------------------------------------------------------------------------

class FlightRecorder:
    """Bounded rings of recent observability entries, dumped as one
    correlated snapshot when something detonates.

    Sources call ``record(source, kind, **fields)`` continuously —
    spans, metric deltas, applied faults, scheduler decisions; each
    source keeps its newest ``keep`` entries.  ``trigger(kind)`` cuts a
    snapshot: every source's entries within ``±window_s`` of the
    trigger instant, plus the trigger details — the serving equivalent
    of a crash dump's "last N seconds from every subsystem".  Snapshot
    retention is bounded too (``max_dumps``); with a ``registry``
    attached, ``flight_recorder_snapshots_total{trigger}`` counts
    dumps.
    """

    def __init__(self, *, clock=time.monotonic, keep: int = 256,
                 window_s: float = 30.0, max_dumps: int = 8,
                 registry=None):
        self.clock = clock
        self.keep = int(keep)
        self.window_s = float(window_s)
        self.max_dumps = int(max_dumps)
        self._rings: Dict[str, collections.deque] = {}
        self.dumps: List[dict] = []
        self._seq = 0
        self._c_snaps = None
        if registry is not None:
            self._c_snaps = registry.counter(
                "flight_recorder_snapshots_total",
                "correlated flight-recorder snapshots cut",
                labelnames=("trigger",))

    def record(self, source: str, kind: str, **fields) -> None:
        ring = self._rings.get(source)
        if ring is None:
            ring = self._rings[source] = collections.deque(
                maxlen=self.keep)
        ring.append((self.clock(), kind, fields))

    def trigger(self, kind: str, **details) -> dict:
        """Cut a correlated snapshot around NOW and retain it."""
        now = self.clock()
        lo, hi = now - self.window_s, now + self.window_s
        snap = {"trigger": kind, "details": dict(details), "ts": now,
                "window_s": self.window_s, "seq": self._seq,
                "sources": {}}
        self._seq += 1
        for source, ring in sorted(self._rings.items()):
            snap["sources"][source] = [
                {"ts": ts, "kind": k, **f}
                for ts, k, f in ring if lo <= ts <= hi]
        self.dumps.append(snap)
        if len(self.dumps) > self.max_dumps:
            del self.dumps[:len(self.dumps) - self.max_dumps]
        if self._c_snaps is not None:
            self._c_snaps.inc(trigger=kind)
        return snap

    @property
    def last(self) -> Optional[dict]:
        return self.dumps[-1] if self.dumps else None

    def save(self, path: str) -> str:
        """Write the retained snapshots as JSON."""
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"snapshots": self.dumps}, f)
        return path
