"""SLO targets, rolling percentiles, multi-window burn-rate alerts.

The registry's :class:`~apex_tpu.observability.registry.Histogram` is
cumulative — right for dashboards, wrong for alerting, where "TTFT p95
over the last five minutes" must FORGET last week.  This module adds the
rolling layer on top:

* :class:`RollingPercentiles` — bounded-memory sliding-window quantile
  estimation.  The window is split into time slots, each slot holds
  fixed-boundary bucket counts (the same boundary semantics as the
  registry histogram), expired slots are dropped as the clock advances,
  and quantiles interpolate within the merged counts —
  ``histogram_quantile`` over a window, O(slots × buckets) memory
  regardless of traffic.
* :class:`SLOTarget` — a declarative objective: "``objective`` of
  ``metric`` observations are good (``value <= threshold``)", e.g.
  TTFT p95 < 200 ms is ``SLOTarget("ttft", 0.2, objective=0.95)``.
* :class:`SLOMonitor` — feeds observations to the percentile windows
  and, per target, to rolling good/total counts; **burn rate** over a
  window is ``bad_fraction / (1 - objective)`` (burn 1.0 = consuming
  the error budget exactly on schedule), and alerts use the standard
  multi-window formulation: a (short, long) pair fires only when BOTH
  windows burn above the pair's threshold — the long window filters
  blips, the short window makes recovery reset the alert quickly.

Wired in: ``ServingMetrics(slo=...)`` feeds ``ttft`` /
``token_latency`` / ``queue_wait``; ``TrainingMonitor(slo=...)`` feeds
``step_time``.  With a registry attached, the monitor exports
``slo_events_total`` / ``slo_burn_rate`` / ``slo_alert`` /
``slo_latency_quantile`` series on every :meth:`SLOMonitor.snapshot`.
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import time
from typing import Dict, Optional, Sequence, Tuple

from apex_tpu.observability.registry import DEFAULT_BUCKETS


class RollingPercentiles:
    """Sliding-window quantiles from time-slotted bucket counts.

    ``window_s`` seconds of history in ``slots`` equal slots; an
    observation lands in the current slot's bucket counts and slots
    older than the window are dropped lazily, so memory is a constant
    ``slots × (len(buckets)+1)`` ints.  ``percentile(q)`` merges the
    live slots and linearly interpolates inside the selected bucket
    (the overflow bucket reports the top finite boundary — the same
    saturation behavior as Prometheus ``histogram_quantile``).
    """

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 window_s: float = 300.0, slots: int = 30,
                 clock=time.monotonic):
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("need at least one bucket boundary")
        if window_s <= 0 or slots < 1:
            raise ValueError("window_s must be > 0 and slots >= 1")
        self.buckets = bs
        self.window_s = float(window_s)
        self.slots = int(slots)
        self.slot_s = self.window_s / self.slots
        self.clock = clock
        # (slot_index, [bucket counts..., overflow]) — append-right,
        # expire-left
        self._ring: collections.deque = collections.deque()

    def _current(self) -> list:
        idx = int(self.clock() // self.slot_s)
        self._expire(idx)
        if not self._ring or self._ring[-1][0] != idx:
            self._ring.append((idx, [0] * (len(self.buckets) + 1)))
        return self._ring[-1][1]

    def _expire(self, idx: int) -> None:
        while self._ring and self._ring[0][0] <= idx - self.slots:
            self._ring.popleft()

    def observe(self, value: float) -> None:
        counts = self._current()
        counts[bisect.bisect_left(self.buckets, float(value))] += 1

    def reset(self) -> None:
        """Drop every live slot — the window restarts empty."""
        self._ring.clear()

    def _merged(self) -> list:
        self._expire(int(self.clock() // self.slot_s))
        merged = [0] * (len(self.buckets) + 1)
        for _, counts in self._ring:
            for i, c in enumerate(counts):
                merged[i] += c
        return merged

    def count(self) -> int:
        return sum(self._merged())

    def percentile(self, q: float) -> float:
        """The q-quantile (``0 <= q <= 1``) of the window, interpolated
        within its bucket; 0.0 on an empty window."""
        merged = self._merged()
        total = sum(merged)
        if not total:
            return 0.0
        rank = q * total
        cum = 0.0
        for i, c in enumerate(merged):
            if not c:
                continue
            if cum + c >= rank:
                if i >= len(self.buckets):     # overflow: saturate
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                return lo + (hi - lo) * max(rank - cum, 0.0) / c
            cum += c
        return self.buckets[-1]                # pragma: no cover


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """``objective`` of ``metric`` observations satisfy
    ``value <= threshold`` — e.g. "95% of TTFTs under 200 ms" is
    ``SLOTarget("ttft", threshold=0.2, objective=0.95)``."""
    metric: str
    threshold: float
    objective: float = 0.99
    name: str = ""

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}")
        if not self.name:
            object.__setattr__(
                self, "name", f"{self.metric}_le_{self.threshold:g}")


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One multi-window alert rule: fire when BOTH the short and the
    long window burn the error budget faster than ``threshold``×."""
    short_s: float
    long_s: float
    threshold: float

    @property
    def label(self) -> str:
        return f"{self.short_s:g}s/{self.long_s:g}s"


# the SRE-book page/ticket pair: 14.4x over (5m, 1h) exhausts a 30-day
# budget in ~2 days; 6x over (30m, 6h) in ~5 days
DEFAULT_BURN_WINDOWS = (BurnWindow(300.0, 3600.0, 14.4),
                        BurnWindow(1800.0, 21600.0, 6.0))


class _WindowedCounts:
    """Rolling (good, total) event counts in time slots, queryable over
    any lookback up to ``max_window_s``."""

    def __init__(self, slot_s: float, max_window_s: float, clock):
        self.slot_s = slot_s
        self.max_slots = max(1, int(round(max_window_s / slot_s)))
        self.clock = clock
        self._ring: collections.deque = collections.deque()  # [idx, good, total]

    def add(self, good: bool) -> None:
        idx = int(self.clock() // self.slot_s)
        while self._ring and self._ring[0][0] <= idx - self.max_slots:
            self._ring.popleft()
        if not self._ring or self._ring[-1][0] != idx:
            self._ring.append([idx, 0, 0])
        slot = self._ring[-1]
        slot[1] += bool(good)
        slot[2] += 1

    def reset(self) -> None:
        """Drop every live slot — the window restarts empty."""
        self._ring.clear()

    def rates(self, window_s: float) -> Tuple[int, int]:
        """(bad, total) over the trailing ``window_s`` seconds."""
        idx = int(self.clock() // self.slot_s)
        n = max(1, int(round(window_s / self.slot_s)))
        bad = total = 0
        for sidx, good, tot in self._ring:
            if sidx > idx - n:
                bad += tot - good
                total += tot
        return bad, total


class SLOMonitor:
    """Rolling SLO evaluation over a set of :class:`SLOTarget`\\ s.

    ``observe(metric, value)`` is the single ingestion point (the
    serving/training monitors call it); everything else is derived on
    read.  With a ``registry`` attached, ``slo_events_total{slo,good}``
    counts every classified event live, and :meth:`snapshot` refreshes
    ``slo_burn_rate{slo,window}`` / ``slo_alert{slo,window}`` /
    ``slo_latency_quantile{metric,quantile}`` gauges.  Memory is
    bounded: per metric one :class:`RollingPercentiles`, per target one
    slot ring covering the longest burn window.
    """

    QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, targets: Sequence[SLOTarget], *,
                 clock=time.monotonic, registry=None,
                 burn_windows: Sequence[BurnWindow] = DEFAULT_BURN_WINDOWS,
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 percentile_window_s: float = 300.0,
                 slots_per_window: int = 30):
        self.targets = tuple(targets)
        names = [t.name for t in self.targets]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO target names: {names}")
        self.burn_windows = tuple(burn_windows)
        self.clock = clock
        self.registry = registry
        self._by_metric: Dict[str, list] = {}
        for t in self.targets:
            self._by_metric.setdefault(t.metric, []).append(t)
        self._pcts = {
            m: RollingPercentiles(buckets=buckets,
                                  window_s=percentile_window_s,
                                  slots=slots_per_window, clock=clock)
            for m in self._by_metric}
        slot_s = (min(w.short_s for w in self.burn_windows)
                  / slots_per_window) if self.burn_windows else 1.0
        max_w = (max(w.long_s for w in self.burn_windows)
                 if self.burn_windows else 1.0)
        self._counts = {t.name: _WindowedCounts(slot_s, max_w, clock)
                        for t in self.targets}
        # window epoch: bumped by reset_windows() at capacity-change
        # boundaries so burn is never computed across a shift
        self.epoch = 0
        self.epoch_tag: Optional[str] = None
        self._c_events = self._g_burn = None
        if registry is not None:
            self._c_events = registry.counter(
                "slo_events_total", "events classified against SLO "
                "targets", labelnames=("slo", "good"))
            self._g_burn = registry.gauge(
                "slo_burn_rate", "error-budget burn multiple per "
                "window", labelnames=("slo", "window"))
            self._g_alert = registry.gauge(
                "slo_alert", "1 while the window pair fires",
                labelnames=("slo", "window"))
            self._g_quant = registry.gauge(
                "slo_latency_quantile", "rolling-window quantile",
                labelnames=("metric", "quantile"))
            self._g_epoch = registry.gauge(
                "slo_window_epoch",
                "burn/percentile window epoch (bumped on reset_windows)")
            self._g_epoch.set(0)

    # -- window epochs -------------------------------------------------------

    def reset_windows(self, epoch: Optional[str] = None) -> None:
        """Forget every rolling window (burn counts AND percentile
        slots) and bump the window epoch.

        The capacity controller calls this when the capacity split
        changes: burn computed over a pre-shift window describes a
        fleet that no longer exists, and acting on it immediately
        re-triggers the next shift — the stale-burn flapping bug.
        After a reset, burn is 0.0 until post-shift traffic refills the
        windows.  ``epoch`` is an optional tag (e.g. ``"shift-3"``)
        surfaced as :attr:`epoch_tag`; :attr:`epoch` is a monotonic
        counter exported as the ``slo_window_epoch`` gauge.
        """
        for c in self._counts.values():
            c.reset()
        for p in self._pcts.values():
            p.reset()
        self.epoch += 1
        self.epoch_tag = epoch
        if self.registry is not None:
            self._g_epoch.set(self.epoch)
            self.registry.event("slo_window_reset", epoch=self.epoch,
                                tag=epoch)

    # -- ingestion -----------------------------------------------------------

    def observe(self, metric: str, value: float) -> None:
        """Classify one observation of ``metric`` against every target
        on it (metrics without a target are ignored — the serving layer
        feeds unconditionally)."""
        targets = self._by_metric.get(metric)
        if not targets:
            return
        self._pcts[metric].observe(value)
        for t in targets:
            good = value <= t.threshold
            self._counts[t.name].add(good)
            if self._c_events is not None:
                self._c_events.inc(slo=t.name, good=str(good).lower())

    # -- derived -------------------------------------------------------------

    def burn_rate(self, target: SLOTarget, window_s: float) -> float:
        """Error-budget burn multiple over the window: 1.0 = burning
        exactly the budgeted rate; 0.0 when the window saw no events."""
        bad, total = self._counts[target.name].rates(window_s)
        if not total:
            return 0.0
        return (bad / total) / (1.0 - target.objective)

    def percentile(self, metric: str, q: float) -> float:
        return self._pcts[metric].percentile(q)

    def alerts(self) -> list:
        """Currently-firing (target, window-pair) alerts."""
        out = []
        for t in self.targets:
            for w in self.burn_windows:
                bs = self.burn_rate(t, w.short_s)
                bl = self.burn_rate(t, w.long_s)
                if bs > w.threshold and bl > w.threshold:
                    out.append({"slo": t.name, "window": w.label,
                                "burn_short": bs, "burn_long": bl,
                                "threshold": w.threshold})
        return out

    def snapshot(self) -> dict:
        """Full rolling-state view; also refreshes the registry gauges
        (burn rates, alert flags, quantiles) when one is attached."""
        firing = {(a["slo"], a["window"]) for a in self.alerts()}
        targets = {}
        for t in self.targets:
            wins = {}
            for w in self.burn_windows:
                wins[w.label] = {
                    "burn_short": self.burn_rate(t, w.short_s),
                    "burn_long": self.burn_rate(t, w.long_s),
                    "threshold": w.threshold,
                    "firing": (t.name, w.label) in firing}
                if self._g_burn is not None:
                    self._g_burn.set(wins[w.label]["burn_short"],
                                     slo=t.name, window=w.label)
                    self._g_alert.set(
                        float(wins[w.label]["firing"]),
                        slo=t.name, window=w.label)
            targets[t.name] = {"metric": t.metric,
                               "threshold": t.threshold,
                               "objective": t.objective,
                               "windows": wins}
        pcts = {}
        for m, rp in self._pcts.items():
            pcts[m] = {f"p{int(q * 100)}": rp.percentile(q)
                       for q in self.QUANTILES}
            pcts[m]["n"] = rp.count()
            if self._g_burn is not None:
                for q in self.QUANTILES:
                    self._g_quant.set(rp.percentile(q), metric=m,
                                      quantile=f"p{int(q * 100)}")
        return {"targets": targets, "percentiles": pcts,
                "alerts": sorted(firing)}
