"""Training-loop telemetry: wrap any train step, get operator metrics.

``TrainingMonitor`` turns a train step — a
:class:`~apex_tpu.resilience.guard.GuardedTrainStep` or any callable —
into the same step plus a metrics tap:

* **step time** (histogram + last-value gauge), measured wall-clock
  around the step's own hard materialization (the guard's telemetry
  readback blocks on the device, so the window covers device work);
* **tokens/s** and, when FLOP accounting is configured, **achieved
  MFU** — the ``tokens_per_step * flops_per_token / dt / peak``
  protocol from ``bench.py``, with the peak supplied directly or
  measured once by :func:`calibrated_peak_flops` (the same
  chained-dependent-matmul probe, so the "peak" is what this silicon
  actually sustains, not the spec sheet);
* **grad-norm / loss / loss-scale series** read from the guard's
  :class:`~apex_tpu.resilience.guard.StepResult` host fields
  (``grad_norm``, ``loss_value``, ``loss_scale_value``) — all carried
  by the ONE readback the guard already performs, so monitoring adds
  no device→host syncs;
* **anomaly / rollback counters** labeled by kind, cross-checkable
  against ``GuardedTrainStep.stats``.

Every step also appends one ``train_step`` record to the registry's
JSONL stream with the keys an alerting pipeline needs
(``step``/``step_time_s``/``tokens_per_s``/``loss``/``grad_norm``/
``anomalies``/...), and the registry's Prometheus snapshot exposes the
same series for scrape-style collection.
"""

from __future__ import annotations

import collections
import functools
import statistics
import time
from typing import Any, Callable, Optional

from apex_tpu.observability.registry import MetricsRegistry

_STEP_KEYS = ("step", "step_time_s", "tokens_per_s", "loss",
              "grad_norm", "anomalies")


def calibrated_peak_flops(chain: int = 32, n: int = 2048,
                          iters: int = 2) -> float:
    """Sustained bf16 matmul FLOP/s on this device — the paired-
    calibration probe from ``bench.py`` (chained DEPENDENT n^3 matmuls
    in one jitted program, hard-synced with a 1-element device→host
    readback; ``block_until_ready`` can lie through remote-device
    tunnels).  Smaller defaults than the bench (one-shot use at monitor
    construction, not a timing-window pair)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(key, (n, n), jnp.bfloat16)

    @jax.jit
    def run(a, b):
        def body(c, _):
            c = jnp.dot(c, b, preferred_element_type=jnp.bfloat16)
            c = c * (1.0 / jnp.maximum(
                jnp.max(jnp.abs(c)), 1.0)).astype(jnp.bfloat16)
            return c, None
        c, _ = jax.lax.scan(body, a, None, length=chain)
        return c

    def sync(x):
        np.asarray(jax.device_get(x[0, 0]))
        return x

    a = sync(run(a, b))                       # compile outside timing
    t0 = time.perf_counter()
    for _ in range(iters):
        a = run(a, b)
    sync(a)
    dt = (time.perf_counter() - t0) / (iters * chain)
    return 2.0 * n ** 3 / dt


class TrainingMonitor:
    """``monitored = TrainingMonitor(...).wrap(step_fn)`` — same
    signature, same return value, metrics recorded per call.

    ``tokens_per_step`` enables the tokens/s gauge;
    ``flops_per_token`` + ``peak_flops`` enable the MFU gauge
    (``peak_flops="calibrated"`` runs :func:`calibrated_peak_flops`
    once, lazily, at the first monitored step).  ``registry`` defaults
    to a fresh :class:`MetricsRegistry`; pass ``stream_path`` to open a
    JSONL event stream on it.  ``clock`` is injectable for tests.

    Straggler visibility: every step sets ``train_step_time_skew`` —
    this step's time over the rolling median of the last
    ``skew_window`` steps, minus one (0.0 = on trend; 1.0 = a 2× step)
    — the single-host "is something stalling" gauge.  Under
    multi-controller JAX, ``straggler_every=N`` additionally
    all-gathers step time across hosts every N steps and sets
    ``train_straggler_ratio`` (slowest/fastest host); it costs a host
    sync per sample, so it defaults to off (0).  ``slo=`` feeds each
    step time to an :class:`~apex_tpu.observability.slo.SLOMonitor`
    as metric ``"step_time"``.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 tokens_per_step: Optional[int] = None,
                 flops_per_token: Optional[float] = None,
                 peak_flops: Any = None,
                 stream_path: Optional[str] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 slo: Any = None,
                 skew_window: int = 32,
                 straggler_every: int = 0):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        if stream_path is not None:
            self.registry.open_stream(stream_path)
        self.clock = clock
        self.tokens_per_step = tokens_per_step
        self.flops_per_token = flops_per_token
        self.peak_flops = peak_flops
        self.slo = slo
        self.straggler_every = straggler_every
        self._recent_dt: collections.deque = \
            collections.deque(maxlen=max(skew_window, 2))
        self.steps = 0
        self._totals = {"anomalies": 0, "rollbacks": 0, "time_s": 0.0}
        r = self.registry
        self._h_step = r.histogram(
            "train_step_time_seconds", "wall seconds per train step")
        self._g_step = r.gauge("train_step_time_s_last",
                               "last step wall seconds")
        self._g_tps = r.gauge("train_tokens_per_s",
                              "tokens per second (last step)")
        self._g_mfu = r.gauge("train_mfu",
                              "achieved fraction of peak FLOP/s")
        self._g_loss = r.gauge("train_loss", "loss (last step)")
        self._g_gnorm = r.gauge("train_grad_norm",
                                "unscaled grad norm (last step)")
        self._g_scale = r.gauge("train_loss_scale",
                                "dynamic loss scale (last step)")
        self._c_steps = r.counter("train_steps_total", "steps run")
        self._c_anom = r.counter(
            "train_anomalies_total", "guard-skipped steps by kind",
            labelnames=("kind",))
        self._c_roll = r.counter("train_rollbacks_total",
                                 "checkpoint rollbacks")
        self._g_skew = r.gauge(
            "train_step_time_skew",
            "step time / rolling median - 1 (0 = on trend)")
        self._g_straggler = r.gauge(
            "train_straggler_ratio",
            "slowest/fastest host step time (multi-controller only)")

    # -- wiring --------------------------------------------------------------

    def wrap(self, step_fn: Callable) -> Callable:
        """Wrap a train step.  A :class:`GuardedTrainStep` (anything
        returning an object with ``grad_norm``/``skipped``/``anomaly``
        fields) gets the full series; a plain callable gets step
        time/tokens/MFU and, when its return value is a scalar-like
        loss, the loss series."""
        @functools.wraps(getattr(step_fn, "__call__", step_fn))
        def monitored(*args, **kwargs):
            t0 = self.clock()
            result = step_fn(*args, **kwargs)
            self.record(self.clock() - t0, result,
                        step=kwargs.get("step"))
            return result
        monitored.monitor = self
        return monitored

    def record(self, dt: float, result: Any = None,
               step: Optional[int] = None) -> None:
        """Record one step from its wall time + (optionally) its
        :class:`StepResult`-like outcome.  Usable directly by loops
        that time themselves."""
        if step is None:
            step = self.steps
        self.steps += 1
        self._totals["time_s"] += dt
        self._h_step.observe(dt)
        self._g_step.set(dt)
        self._c_steps.inc()
        rec = {"step": int(step), "step_time_s": dt,
               "anomalies": self._totals["anomalies"]}

        # skew vs the rolling median of RECENT steps (this step is
        # appended after the read, so a stall shows against the trend
        # rather than diluting it)
        med = statistics.median(self._recent_dt) if self._recent_dt else dt
        skew = (dt / med - 1.0) if med > 0 else 0.0
        self._recent_dt.append(dt)
        self._g_skew.set(skew)
        rec["step_time_skew"] = skew
        if self.slo is not None:
            self.slo.observe("step_time", dt)
        if self.straggler_every and self.steps % self.straggler_every == 0:
            ratio = self._straggler_ratio(dt)
            if ratio is not None:
                self._g_straggler.set(ratio)
                rec["straggler_ratio"] = ratio

        if self.tokens_per_step:
            tps = self.tokens_per_step / dt if dt > 0 else 0.0
            self._g_tps.set(tps)
            rec["tokens_per_s"] = tps
            if self.flops_per_token:
                peak = self._resolve_peak()
                if peak:
                    mfu = tps * self.flops_per_token / peak
                    self._g_mfu.set(mfu)
                    rec["mfu"] = mfu

        gnorm = getattr(result, "grad_norm", None)
        if gnorm is not None:
            self._g_gnorm.set(gnorm)
            rec["grad_norm"] = float(gnorm)
        loss = getattr(result, "loss_value", None)
        if loss is None and result is not None \
                and not hasattr(result, "params"):
            try:                          # plain step returning a loss
                loss = float(result)
            except (TypeError, ValueError):
                loss = None
        if loss is not None:
            self._g_loss.set(loss)
            rec["loss"] = float(loss)
        scale = getattr(result, "loss_scale_value", None)
        if scale is not None:
            self._g_scale.set(scale)
            rec["loss_scale"] = float(scale)
        if getattr(result, "skipped", False):
            kind = getattr(result, "anomaly", None) or "unknown"
            self._totals["anomalies"] += 1
            rec["anomalies"] = self._totals["anomalies"]
            rec["anomaly"] = kind
            self._c_anom.inc(kind=kind)
        if getattr(result, "rolled_back", False):
            self._totals["rollbacks"] += 1
            rec["rolled_back"] = True
            self._c_roll.inc()
        self.registry.event("train_step", **rec)

    @staticmethod
    def _straggler_ratio(dt: float) -> Optional[float]:
        """slowest/fastest host step time via a process all-gather;
        None single-controller (the skew gauge covers that case)."""
        import jax
        if jax.process_count() <= 1:
            return None
        try:
            import numpy as np
            from jax.experimental import multihost_utils

            all_dt = np.asarray(multihost_utils.process_allgather(
                np.float32(dt)))
            lo = float(np.min(all_dt))
            return float(np.max(all_dt)) / max(lo, 1e-12)
        except Exception:           # pragma: no cover - backend-specific
            return None

    def _resolve_peak(self) -> Optional[float]:
        if self.peak_flops == "calibrated":
            self.peak_flops = calibrated_peak_flops()
        return self.peak_flops

    # -- summaries -----------------------------------------------------------

    @property
    def stats(self) -> dict:
        """Host-side rollup, shape-compatible with
        ``GuardedTrainStep.stats`` on the shared keys."""
        t = self._totals
        mean = t["time_s"] / self.steps if self.steps else 0.0
        out = {"steps": self.steps, "skipped": t["anomalies"],
               "rollbacks": t["rollbacks"],
               "mean_step_time_s": mean,
               "tokens_per_s": (self.tokens_per_step / mean
                                if self.tokens_per_step and mean else None)}
        return out

    def report(self, guard=None, scaler=None, scaler_state=None) -> dict:
        """End-of-run summary.  Pass the guard to fold in its full
        per-kind counters; pass ``scaler, scaler_state`` to fold in
        ``LossScaler.stats`` (one 4-scalar readback, at report time
        only)."""
        out = dict(self.stats)
        if guard is not None:
            out["guard"] = dict(guard.stats)
        if scaler is not None and scaler_state is not None:
            out["scaler"] = scaler.stats(scaler_state)
        return out

    def close(self) -> None:
        self.registry.close()
