"""apex-tpu build (reference: apex ``setup.py``, ~900 lines of flag-gated
CUDA extension builds — ``--cpp_ext --cuda_ext --fmha --bnp ...``).

The TPU rebuild needs none of that for device code: every kernel is
JAX/Pallas, shipped as Python.  The one native artifact is the host
runtime (``apex_tpu/csrc/host_runtime.cpp`` — threaded buffer packing and
parallel file IO used by bucketing and gpu_direct_storage).  Mirroring the
reference's gating, it is built when ``APEX_TPU_CPP_EXT=1`` (or the
``--cpp_ext`` global option) is set and skipped otherwise; at runtime
``apex_tpu.utils.native`` also compiles it on demand and always has a
pure-Python fallback, so a wheel without it is functional.
"""

import os
import subprocess
import sys

from setuptools import setup
from setuptools.command.build_py import build_py


def _want_cpp_ext() -> bool:
    if os.environ.get("APEX_TPU_CPP_EXT") == "1":
        return True
    if "--cpp_ext" in sys.argv:
        sys.argv.remove("--cpp_ext")
        return True
    return False


class BuildWithNative(build_py):
    def run(self):
        if _want_cpp_ext():
            src = os.path.join("apex_tpu", "csrc", "host_runtime.cpp")
            out = os.path.join("apex_tpu", "csrc",
                               "libapex_host_runtime.so")
            print(f"building native host runtime: {src} -> {out}")
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-pthread", src, "-o", out],
                check=True)
        super().run()


setup(
    cmdclass={"build_py": BuildWithNative},
    package_data={"apex_tpu": ["csrc/*.cpp", "csrc/*.so"]},
)
