#!/usr/bin/env python
"""Minimal DDP example (reference:
``examples/simple/distributed/distributed_data_parallel.py`` — ~60 lines:
init_process_group, wrap a toy model in apex DDP, train on random data).

The TPU translation is the explicit-collective form: a 1-axis mesh, the
model run per-device under ``shard_map``, and gradients reduced with
``apex_tpu.parallel.allreduce_gradients`` (the bucketed-allreduce
equivalent — XLA fuses the psums).  Works on any device count, including
the 8 virtual CPU devices:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
    JAX_PLATFORMS=cpu python distributed_data_parallel.py
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import DistributedDataParallel


def main():
    n_dev = len(jax.devices())
    mesh = jax.make_mesh((n_dev,), ("data",),
                     axis_types=(jax.sharding.AxisType.Auto,))

    def model(params, x):
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        return h @ params["w2"] + params["b2"]

    rng = np.random.RandomState(0)
    params = {"w1": jnp.asarray(rng.randn(16, 32), jnp.float32) * 0.1,
              "b1": jnp.zeros((32,)),
              "w2": jnp.asarray(rng.randn(32, 4), jnp.float32) * 0.1,
              "b2": jnp.zeros((4,))}

    ddp = DistributedDataParallel(model, mesh=mesh, axis_name="data")

    def local_step(params, x, y):
        # runs per-device on the local batch shard: local grads first,
        # then ONE explicit allreduce (apex's bucketed-hook staging)
        params = ddp.mark_local(params)

        def loss_fn(p):
            pred = model(p, x)
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = ddp.reduce(grads)                         # the DDP hook
        loss = jax.lax.pmean(loss, "data")
        return loss, grads

    @jax.jit
    def train_step(params, x, y):
        loss, grads = shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P()))(params, x, y)
        return loss, jax.tree_util.tree_map(
            lambda p, g: p - 0.05 * g, params, grads)

    batch = 8 * n_dev
    for step in range(20):
        x = jnp.asarray(rng.randn(batch, 16), jnp.float32)
        y = jnp.asarray(rng.randn(batch, 4), jnp.float32)
        loss, params = train_step(params, x, y)
        if step % 5 == 0 or step == 19:
            print(f"step {step:3d}  loss {float(loss):.5f}")
    print(f"DONE devices={n_dev}")


if __name__ == "__main__":
    main()
