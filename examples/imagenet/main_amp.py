#!/usr/bin/env python
"""ImageNet training CLI — apex_tpu rebuild of the reference's flagship
example (``examples/imagenet/main_amp.py``: torchvision ResNet + amp
O0–O3 + apex DDP + optional FusedSGD + a CUDA-stream data prefetcher).

TPU translation of each piece:

* model      — ``apex_tpu.models.resnet`` (NHWC bottleneck ResNet,
               SyncBN-able batch norm)
* amp        — ``apex_tpu.amp.initialize(opt_level=O0|O1|O2|O3)`` +
               ``scale_loss`` / ``unscale_step`` inside one jitted step
* DDP        — GSPMD data parallelism: a 1-axis device mesh, batch
               sharded over "data", params replicated; XLA inserts the
               gradient psum (the bucketed-allreduce equivalent)
* FusedSGD   — packed-bucket Pallas optimizer (``--fused-sgd``, default)
               vs a plain hand-written SGD (``--no-fused-sgd``)
* prefetcher — a background thread stages the next host batch and
               ``jax.device_put``s it while the current step runs (the
               ``data_prefetcher`` stream-overlap equivalent)

Data is synthetic by default (``--synthetic``, the only mode wired here:
the benchmark protocol needs no JPEG pipeline), shaped and scaled like
ImageNet; pass ``--steps`` to bound the run.

Run:  python examples/imagenet/main_amp.py --arch resnet50 \\
          --batch-size 256 --opt-level O2 --steps 100
"""

from __future__ import annotations

import argparse
import queue
import threading
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description="apex_tpu imagenet + amp")
    p.add_argument("--arch", default="resnet50",
                   choices=["resnet50", "resnet18"])
    p.add_argument("--batch-size", type=int, default=256,
                   help="GLOBAL batch size (split over the data axis)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--opt-level", default="O1",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--loss-scale", default=None,
                   help='None, a float, or "dynamic"')
    p.add_argument("--sync-bn", action="store_true",
                   help="cross-device BN stats (apex convert_syncbn_model)")
    p.add_argument("--no-fused-sgd", dest="fused_sgd", action="store_false")
    p.add_argument("--synthetic", action="store_true", default=True)
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


class Prefetcher:
    """Host-side double buffering: generate + device_put the next batch
    while the device runs the current step."""

    def __init__(self, make_batch, put, depth=2):
        self.q = queue.Queue(maxsize=depth)
        self.make_batch, self.put = make_batch, put
        self.stop = threading.Event()
        self.error = None
        self.thread = threading.Thread(target=self._work, daemon=True)
        self.thread.start()

    def _work(self):
        try:
            while not self.stop.is_set():
                batch = self.put(*self.make_batch())
                while not self.stop.is_set():
                    try:
                        self.q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:          # surface in next(), don't hang
            self.error = e
            self.stop.set()

    def next(self):
        while True:
            try:
                return self.q.get(timeout=0.5)
            except queue.Empty:
                if self.error is not None:
                    raise RuntimeError("prefetcher worker died") \
                        from self.error

    def close(self):
        self.stop.set()
        while not self.q.empty():
            self.q.get_nowait()
        self.thread.join(timeout=2)


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.models.resnet import resnet18, resnet50
    from apex_tpu.optimizers import FusedSGD

    n_dev = len(jax.devices())
    mesh = jax.make_mesh((n_dev,), ("data",),
                     axis_types=(jax.sharding.AxisType.Auto,))
    data_sharding = NamedSharding(mesh, P("data"))
    replicated = NamedSharding(mesh, P())
    if args.batch_size % n_dev:
        raise SystemExit(f"--batch-size must divide {n_dev} devices")

    half = jnp.bfloat16
    compute_dtype = half if args.opt_level in ("O2", "O3") else jnp.float32
    make = resnet50 if args.arch == "resnet50" else resnet18
    model = make(num_classes=args.num_classes,
                 axis_name=None,          # GSPMD: SyncBN comes from sharding
                 dtype=compute_dtype)
    if args.sync_bn:
        # under GSPMD the batch is globally sharded, so plain BN stats ARE
        # global-batch stats — matching apex sync BN semantics with no
        # explicit collective.  (shard_map recipes set axis_name instead.)
        pass

    sgd = FusedSGD(lr=args.lr, momentum=args.momentum,
                   weight_decay=args.weight_decay,
                   master_weights=args.opt_level == "O2") if args.fused_sgd \
        else None

    params = model.init_params(jax.random.PRNGKey(args.seed))
    bn_state = model.init_state()

    loss_scale = args.loss_scale
    if isinstance(loss_scale, str):
        if loss_scale in ("None", "none"):
            loss_scale = None
        elif loss_scale != "dynamic":
            loss_scale = float(loss_scale)
    state = amp.initialize(model.apply, sgd, opt_level=args.opt_level,
                           loss_scale=loss_scale)
    params = state.cast_params(params)
    scaler_state = state.scaler.init()

    if sgd is not None:
        opt_state = sgd.init(params)
    else:
        # f32 momentum regardless of param dtype (the update promotes to
        # f32; a bf16 init would flip dtype after step 1 -> recompile)
        opt_state = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)

    params, bn_state, opt_state = jax.device_put(
        (params, bn_state, opt_state), replicated)

    rng = np.random.RandomState(args.seed)
    shape = (args.batch_size, args.image_size, args.image_size, 3)

    def make_batch():
        x = rng.randn(*shape).astype(np.float32)
        y = rng.randint(0, args.num_classes, (args.batch_size,))
        return x, y

    def put(x, y):
        return (jax.device_put(x, data_sharding),
                jax.device_put(y, data_sharding))

    def loss_fn(p, bn, x, y, scaler_state):
        # state.apply_fn is the (possibly O1-autocast) model apply
        logits, new_bn = state.apply_fn(p, bn, x, training=True)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
        return amp.scale_loss(jnp.mean(nll), scaler_state), new_bn

    @jax.jit
    def train_step(params, bn_state, opt_state, scaler_state, x, y):
        (loss, new_bn), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, bn_state, x, y, scaler_state)
        loss = loss / scaler_state.loss_scale
        if sgd is not None:
            params, opt_state, scaler_state, _ = amp.unscale_step(
                sgd, grads, params, opt_state, state.scaler, scaler_state)
        else:  # hand-written momentum SGD baseline
            inv = 1.0 / scaler_state.loss_scale
            finf = amp.LossScaler.found_inf(grads)
            keep = 1.0 - finf          # 0 on overflow: skip the update
            opt_state = jax.tree_util.tree_map(
                lambda m, g: jnp.where(
                    finf > 0, m,
                    args.momentum * m + g.astype(jnp.float32) * inv),
                opt_state, grads)
            params = jax.tree_util.tree_map(
                lambda p, m: (p - keep * args.lr
                              * (m + args.weight_decay
                                 * p.astype(jnp.float32))).astype(p.dtype),
                params, opt_state)
            scaler_state = state.scaler.update(scaler_state, finf)
        return params, new_bn, opt_state, scaler_state, loss

    pre = Prefetcher(make_batch, put)
    try:
        # warmup/compile
        x, y = pre.next()
        params, bn_state, opt_state, scaler_state, loss = train_step(
            params, bn_state, opt_state, scaler_state, x, y)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        seen = 0
        for step in range(1, args.steps + 1):
            x, y = pre.next()
            params, bn_state, opt_state, scaler_state, loss = train_step(
                params, bn_state, opt_state, scaler_state, x, y)
            seen += args.batch_size
            if step % args.print_freq == 0 or step == args.steps:
                loss_host = float(loss)
                dt = time.perf_counter() - t0
                print(f"step {step:5d}  loss {loss_host:.4f}  "
                      f"{seen / dt:9.1f} img/s  "
                      f"scale {float(scaler_state.loss_scale):.0f}",
                      flush=True)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        print(f"DONE arch={args.arch} opt_level={args.opt_level} "
              f"devices={n_dev} throughput={seen / dt:.1f} img/s")
    finally:
        pre.close()


if __name__ == "__main__":
    main()
