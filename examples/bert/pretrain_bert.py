#!/usr/bin/env python
"""BERT pretraining recipe — BASELINE workload 2 (reference lineage:
NVIDIA's MLPerf BERT submissions are the reason apex carries
``DistributedFusedLAMB``, ``fmha`` and FastLayerNorm; apex itself ships
no BERT script, so this example IS the missing recipe wired from
apex-surface parts).

The apex-entrypoint wiring, per BASELINE ("FusedLAMB + FusedLayerNorm +
amp O2 -> bf16"):

* model  — ``apex_tpu.models.bert`` (MixedFusedLayerNorm + flash
           attention inside)
* opt    — ``FusedLAMB`` (or ``FusedMixedPrecisionLamb`` under O2: fp32
           master weights over bf16 model params)
* amp O2 — params cast to bf16 (LN kept fp32), loss scaling
* DP     — GSPMD over all devices, batch sharded on "data"

Synthetic MLM batches (15% masked).  Reports sequences/s and achieved
model FLOP/s.

Run:  python examples/bert/pretrain_bert.py --config large \\
          --batch-size 32 --seq-len 512 --steps 50
"""

from __future__ import annotations

import argparse
import time

import numpy as np

_CONFIGS = {
    # hidden, layers, heads
    "tiny": (128, 2, 2),
    "base": (768, 12, 12),
    "large": (1024, 24, 16),
}


def parse_args():
    p = argparse.ArgumentParser(description="apex_tpu BERT pretrain")
    p.add_argument("--config", default="large", choices=sorted(_CONFIGS))
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--vocab-size", type=int, default=30528)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--opt-level", default="O2",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--lr", type=float, default=4e-4)
    p.add_argument("--weight-decay", type=float, default=0.01)
    p.add_argument("--mask-prob", type=float, default=0.15)
    p.add_argument("--remat", action="store_true",
                   help="per-layer activation recompute (the round-5 "
                        "measured best single-chip config runs WITHOUT "
                        "remat at micro-batch 16 — see bench.py)")
    p.add_argument("--optimizer-layout", default="per_leaf",
                   choices=["per_leaf", "packed"],
                   help="per_leaf: XLA-fused per-leaf state, the "
                        "single-chip speed path (~1.9x faster steps); "
                        "packed: the (rows, 128) multi-tensor engine "
                        "(the ZeRO/distributed layout)")
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.models.bert import BertConfig, BertModel
    from apex_tpu.optimizers import FusedLAMB, FusedMixedPrecisionLamb

    hidden, layers, heads = _CONFIGS[args.config]
    n_dev = len(jax.devices())
    mesh = jax.make_mesh((n_dev,), ("data",),
                     axis_types=(jax.sharding.AxisType.Auto,))
    data_sharding = NamedSharding(mesh, P("data"))
    replicated = NamedSharding(mesh, P())
    if args.batch_size % n_dev:
        raise SystemExit(f"--batch-size must divide {n_dev} devices")

    # O2/O3 cast the model to bf16; O1 keeps f32 params and relies on the
    # per-op autocast interpreter (apex O1 semantics)
    half = jnp.bfloat16
    cfg = BertConfig(
        vocab_size=args.vocab_size, hidden_size=hidden, num_layers=layers,
        num_attention_heads=heads, max_seq_len=args.seq_len,
        remat=args.remat,
        dtype=half if args.opt_level in ("O2", "O3") else jnp.float32)
    model = BertModel(cfg)
    params = model.init_params(jax.random.PRNGKey(args.seed))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))

    # O2: FusedMixedPrecisionLamb = LAMB + fp32 master weights
    lamb_cls = (FusedMixedPrecisionLamb if args.opt_level == "O2"
                else FusedLAMB)
    lamb = lamb_cls(lr=args.lr, weight_decay=args.weight_decay,
                    bucketed=args.optimizer_layout == "packed")
    state = amp.initialize(model.apply, lamb, opt_level=args.opt_level)
    params = state.cast_params(params)
    scaler_state = state.scaler.init()
    opt_state = lamb.init(params)
    params, opt_state = jax.device_put((params, opt_state), replicated)

    rng = np.random.RandomState(args.seed)

    def make_batch():
        tokens = rng.randint(4, args.vocab_size,
                             (args.batch_size, args.seq_len))
        masked = rng.rand(args.batch_size, args.seq_len) < args.mask_prob
        labels = np.where(masked, tokens, -1)
        tokens = np.where(masked, 3, tokens)          # [MASK] id = 3
        types = np.zeros_like(tokens)
        return (jax.device_put(tokens, data_sharding),
                jax.device_put(labels, data_sharding),
                jax.device_put(types, data_sharding))

    # O1: the autocast interpreter wraps the WHOLE loss (per-op policy);
    # other levels run the loss at the model's own dtype
    raw_loss = (amp.autocast(model.loss)
                if state.properties.patch_torch_functions else model.loss)

    @jax.jit
    def train_step(params, opt_state, scaler_state, tokens, labels, types):
        def loss_fn(p):
            raw = raw_loss(p, tokens, labels, token_type_ids=types)
            return amp.scale_loss(raw, scaler_state)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        loss = loss / scaler_state.loss_scale
        params, opt_state, scaler_state, _ = amp.unscale_step(
            lamb, grads, params, opt_state, state.scaler, scaler_state)
        return params, opt_state, scaler_state, loss

    # compile + warmup
    batch = make_batch()
    params, opt_state, scaler_state, loss = train_step(
        params, opt_state, scaler_state, *batch)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    seen = 0
    for step in range(1, args.steps + 1):
        batch = make_batch()
        params, opt_state, scaler_state, loss = train_step(
            params, opt_state, scaler_state, *batch)
        seen += args.batch_size
        if step % args.print_freq == 0 or step == args.steps:
            print(f"step {step:5d}  mlm_loss {float(loss):.4f}  "
                  f"{seen / (time.perf_counter() - t0):8.2f} seq/s",
                  flush=True)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    seq_s = seen / dt
    flops = 6 * n_params * args.seq_len * seq_s   # fwd+bwd per token
    print(f"DONE config={args.config} ({n_params/1e6:.1f}M params) "
          f"opt_level={args.opt_level} devices={n_dev} "
          f"throughput={seq_s:.2f} seq/s "
          f"achieved={flops/1e12:.2f} TFLOP/s")


if __name__ == "__main__":
    main()
