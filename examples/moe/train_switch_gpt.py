#!/usr/bin/env python
"""Switch-GPT training via expert parallelism (beyond-reference: MoE is
not in apex; this recipe exercises
``apex_tpu.transformer.expert_parallel`` through the GPT flagship).

Experts are sharded over the ``expert`` mesh axis, which doubles as the
data axis (each device trains on its own token shard — the standard
Switch/GShard deployment).  Dense params stay replicated and their
grads pmean; expert-stack grads are per-shard by construction.

Run:  python examples/moe/train_switch_gpt.py --n-experts 8 \\
          --top-k 1 --steps 20
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description="apex_tpu Switch-GPT")
    p.add_argument("--n-experts", type=int, default=8)
    p.add_argument("--top-k", type=int, default=1,
                   help="1 = Switch, 2 = GShard")
    p.add_argument("--capacity-factor", type=float, default=1.25)
    p.add_argument("--batch-per-device", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=32768)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--print-freq", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.expert_parallel import (
        is_gpt_expert_leaf, localize_expert_params, reduce_moe_grads)

    ep = len(jax.devices())
    if args.n_experts % ep:
        raise SystemExit(
            f"--n-experts must be divisible by the device count ({ep})")

    serial_cfg = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        max_seq_len=args.seq_len, dtype=jnp.bfloat16,
        n_experts=args.n_experts, moe_top_k=args.top_k,
        moe_capacity_factor=args.capacity_factor)
    init_model = GPTModel(serial_cfg)
    params = init_model.init_params(jax.random.PRNGKey(args.seed))

    if ep > 1:
        import dataclasses
        cfg = dataclasses.replace(serial_cfg, expert_axis="expert",
                                  expert_parallel_size=ep)
    else:
        cfg = serial_cfg
    model = GPTModel(cfg)
    nl = args.n_experts // ep

    is_expert = is_gpt_expert_leaf

    # shard the expert stacks (leading (ep, nl, ...) axis); replicate
    # rest.  ep=1 trains the plain serial form (no extra axis).
    sharded = jax.tree_util.tree_map_with_path(
        lambda p, x: x.reshape(ep, nl, *x.shape[1:])
        if ep > 1 and is_expert(p) else x, params)
    specs = jax.tree_util.tree_map_with_path(
        lambda p, x: P("expert") if is_expert(p) else P(), params)
    mesh = jax.make_mesh((ep,), ("expert",),
                         axis_types=(jax.sharding.AxisType.Auto,))

    adam = FusedAdam(lr=args.lr)
    # optimizer runs OUTSIDE shard_map on the stacked (ep, nl, ...)
    # pytree: the packed buckets are ordinary arrays whose sharding GSPMD
    # propagates from the param shardings
    opt_state = adam.init(sharded)

    if ep > 1:
        def grad_fn(p, tokens, targets):
            # differentiate the LOCAL per-device loss, then apply the
            # shared EP reduction recipe (reduce_moe_grads)
            local = localize_expert_params(p)
            loss, grads = jax.value_and_grad(model.loss)(local, tokens,
                                                         targets)
            grads = reduce_moe_grads(grads, "expert")
            return jax.lax.pmean(loss, "expert"), grads

        @jax.jit
        def train_step(p, opt_state, tokens, targets):
            loss, grads = shard_map(
                grad_fn, mesh=mesh,
                in_specs=(specs, P("expert"), P("expert")),
                out_specs=(P(), specs), check_vma=False)(p, tokens,
                                                         targets)
            new_p, new_opt = adam.step(grads, p, opt_state)
            return loss, new_p, new_opt
    else:
        @jax.jit
        def train_step(p, opt_state, tokens, targets):
            loss, grads = jax.value_and_grad(model.loss)(p, tokens,
                                                         targets)
            new_p, new_opt = adam.step(grads, p, opt_state)
            return loss, new_p, new_opt

    rng = np.random.RandomState(args.seed)
    B = ep * args.batch_per_device

    def make_batch():
        return (jnp.asarray(rng.randint(0, args.vocab,
                                        (B, args.seq_len))),
                jnp.asarray(rng.randint(0, args.vocab,
                                        (B, args.seq_len))))

    tokens, targets = make_batch()
    loss, sharded, opt_state = train_step(sharded, opt_state, tokens,
                                          targets)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for step in range(1, args.steps + 1):
        tokens, targets = make_batch()
        loss, sharded, opt_state = train_step(sharded, opt_state,
                                              tokens, targets)
        if step % args.print_freq == 0 or step == args.steps:
            tok_s = step * B * args.seq_len / (time.perf_counter() - t0)
            print(f"step {step:4d}  loss {float(loss):8.4f}  "
                  f"{tok_s:10.0f} tok/s", flush=True)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    print(f"DONE experts={args.n_experts} top_k={args.top_k} devices={ep}"
          f" throughput={args.steps * B * args.seq_len / dt:.0f} tok/s")


if __name__ == "__main__":
    main()
