#!/usr/bin/env python
"""DCGAN + amp example (reference: ``examples/dcgan/main_amp.py`` — the
apex example showing amp with MULTIPLE models/optimizers/losses: a
generator and a discriminator, each with its own loss scaler, via
``amp.initialize([netD, netG], [optD, optG], num_losses=3)``).

The functional translation keeps the interesting part — two models, two
fused optimizers, three scaled losses (errD_real, errD_fake, errG) with
INDEPENDENT loss scalers — inside two jitted steps.  Data is synthetic
64x64 images (the reference defaults to torchvision datasets but any
image folder; the GAN math is identical).

Run:  python examples/dcgan/main_amp.py --steps 50 --opt-level O1
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description="apex_tpu dcgan + amp")
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--image-size", type=int, default=64, choices=[64],
                   help="the DCGAN topology is fixed at 64x64 (4 stride-2 "
                        "stages), like the reference architecture")
    p.add_argument("--nz", type=int, default=100, help="latent dim")
    p.add_argument("--ngf", type=int, default=64)
    p.add_argument("--ndf", type=int, default=64)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--opt-level", default="O1",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.optimizers import FusedAdam

    _DN = ("NHWC", "HWIO", "NHWC")

    def conv(x, w, stride):
        return jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=_DN)

    def deconv(x, w, stride):
        return jax.lax.conv_transpose(
            x, w.astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=_DN)

    def lrelu(x):
        return jnp.where(x > 0, x, 0.2 * x)

    key = jax.random.PRNGKey(args.seed)

    def winit(key, *shape):
        return 0.02 * jax.random.normal(key, shape, jnp.float32)

    nz, ngf, ndf = args.nz, args.ngf, args.ndf
    kg = jax.random.split(key, 5)
    # generator: z (1x1) -> 4x4 -> 8 -> 16 -> 32 -> 64
    gen_params = {
        "p0": winit(kg[0], 4, 4, nz, ngf * 8),        # project via deconv
        "d1": winit(kg[1], 4, 4, ngf * 8, ngf * 4),
        "d2": winit(kg[2], 4, 4, ngf * 4, ngf * 2),
        "d3": winit(kg[3], 4, 4, ngf * 2, ngf),
        "d4": winit(kg[4], 4, 4, ngf, 3),
    }
    kd = jax.random.split(jax.random.fold_in(key, 1), 5)
    disc_params = {
        "c1": winit(kd[0], 4, 4, 3, ndf),
        "c2": winit(kd[1], 4, 4, ndf, ndf * 2),
        "c3": winit(kd[2], 4, 4, ndf * 2, ndf * 4),
        "c4": winit(kd[3], 4, 4, ndf * 4, ndf * 8),
        "head": winit(kd[4], 4 * 4 * ndf * 8, 1),
    }

    # O2/O3 run the nets in bf16: cast the activations entering them
    # (weights are cast once by cast_params below)
    half_dtype = (jnp.bfloat16 if args.opt_level in ("O2", "O3")
                  else jnp.float32)

    def generator(p, z):
        z = z.astype(half_dtype)
        x = z.reshape(z.shape[0], 1, 1, nz)
        x = jax.nn.relu(deconv(x, p["p0"], 4))            # 4x4
        x = jax.nn.relu(deconv(x, p["d1"], 2))            # 8x8
        x = jax.nn.relu(deconv(x, p["d2"], 2))            # 16
        x = jax.nn.relu(deconv(x, p["d3"], 2))            # 32
        return jnp.tanh(deconv(x, p["d4"], 2))            # 64

    def discriminator(p, x):
        x = x.astype(half_dtype)
        x = lrelu(conv(x, p["c1"], 2))                    # 32
        x = lrelu(conv(x, p["c2"], 2))                    # 16
        x = lrelu(conv(x, p["c3"], 2))                    # 8
        x = lrelu(conv(x, p["c4"], 2))                    # 4
        x = x.reshape(x.shape[0], -1).astype(jnp.float32)
        return (x @ p["head"])[:, 0]

    optD = FusedAdam(lr=args.lr, betas=(args.beta1, 0.999))
    optG = FusedAdam(lr=args.lr, betas=(args.beta1, 0.999))

    # apex: amp.initialize([netD, netG], [optD, optG], num_losses=3) —
    # one scaler per loss; here each loss gets its own scaler state
    stateD = amp.initialize(discriminator, optD, opt_level=args.opt_level)
    stateG = amp.initialize(generator, optG, opt_level=args.opt_level)
    disc_params = stateD.cast_params(disc_params)
    gen_params = stateG.cast_params(gen_params)
    scalers = [stateD.scaler.init() for _ in range(2)] + \
        [stateG.scaler.init()]

    optD_state = optD.init(disc_params)
    optG_state = optG.init(gen_params)
    disc_apply, gen_apply = stateD.apply_fn, stateG.apply_fn

    def bce_logits(logits, target):
        # -(t*log s + (1-t)*log(1-s)) in the stable logits form
        return jnp.mean(jnp.maximum(logits, 0) - logits * target
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    @jax.jit
    def d_step(disc_params, optD_state, gen_params, s_real, s_fake,
               real, z):
        fake = gen_apply(gen_params, z)

        def loss_real(p):
            return amp.scale_loss(
                bce_logits(disc_apply(p, real), 1.0), s_real)

        def loss_fake(p):
            return amp.scale_loss(
                bce_logits(disc_apply(p, jax.lax.stop_gradient(fake)),
                           0.0), s_fake)

        # two backwards, two scalers — apex loss_id=0 and loss_id=1.
        # report errD with the scales used THIS step (update comes after),
        # and skip the whole update on overflow in either backward.
        lr_val, g_real = jax.value_and_grad(loss_real)(disc_params)
        lf_val, g_fake = jax.value_and_grad(loss_fake)(disc_params)
        errD = lr_val / s_real.loss_scale + lf_val / s_fake.loss_scale
        grads = jax.tree_util.tree_map(
            lambda a, b: a / s_real.loss_scale + b / s_fake.loss_scale,
            g_real, g_fake)
        finf_r = amp.LossScaler.found_inf(g_real)
        finf_f = amp.LossScaler.found_inf(g_fake)
        noop = jnp.maximum(finf_r, finf_f).astype(jnp.int32)
        disc_params, optD_state = optD.step(grads, disc_params, optD_state,
                                            noop_flag=noop)
        s_real = stateD.scaler.update(s_real, finf_r)
        s_fake = stateD.scaler.update(s_fake, finf_f)
        return disc_params, optD_state, s_real, s_fake, errD

    @jax.jit
    def g_step(gen_params, optG_state, disc_params, s_gen, z):
        def loss_gen(p):
            fake = gen_apply(p, z)
            return amp.scale_loss(
                bce_logits(disc_apply(disc_params, fake), 1.0), s_gen)

        lg_val, grads = jax.value_and_grad(loss_gen)(gen_params)
        errG = lg_val / s_gen.loss_scale       # this step's scale
        gen_params, optG_state, s_gen, _ = amp.unscale_step(
            optG, grads, gen_params, optG_state, stateG.scaler, s_gen)
        return gen_params, optG_state, s_gen, errG

    rng = np.random.RandomState(args.seed)
    t0 = time.perf_counter()
    for step in range(1, args.steps + 1):
        real = jnp.asarray(rng.randn(args.batch_size, args.image_size,
                                     args.image_size, 3), jnp.float32)
        z1 = jnp.asarray(rng.randn(args.batch_size, nz), jnp.float32)
        z2 = jnp.asarray(rng.randn(args.batch_size, nz), jnp.float32)
        disc_params, optD_state, scalers[0], scalers[1], errD = d_step(
            disc_params, optD_state, gen_params, scalers[0], scalers[1],
            real, z1)
        gen_params, optG_state, scalers[2], errG = g_step(
            gen_params, optG_state, disc_params, scalers[2], z2)
        if step % args.print_freq == 0 or step == args.steps:
            print(f"step {step:4d}  errD {float(errD):.4f}  "
                  f"errG {float(errG):.4f}", flush=True)
    dt = time.perf_counter() - t0
    print(f"DONE steps={args.steps} opt_level={args.opt_level} "
          f"{args.steps * args.batch_size / dt:.1f} img/s")


if __name__ == "__main__":
    main()
