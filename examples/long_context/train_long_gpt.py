#!/usr/bin/env python
"""Long-context GPT training via context parallelism (beyond-reference:
the apex reference has no long-context mechanism; this recipe uses
``apex_tpu.transformer.context_parallel`` — ring attention or Ulysses
all-to-all — to train on sequences that do not fit one device's
attention memory).

The GLOBAL sequence is sharded contiguously over the ``context`` mesh
axis; each device holds ``seq/n`` tokens and attention runs over the
full global sequence (ring: KV rotates over ICI; ulysses: all-to-all
head resharding into the Pallas flash kernel).  Loss and grads are
exactly the serial model's (see tests/test_context_parallel.py).

Run:  python examples/long_context/train_long_gpt.py \\
          --seq-len 8192 --mechanism ring --steps 10
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description="apex_tpu long-context GPT")
    p.add_argument("--seq-len", type=int, default=8192,
                   help="GLOBAL sequence length (split over devices)")
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=32768)
    p.add_argument("--mechanism", default="ring",
                   choices=["ring", "ulysses"])
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--print-freq", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.utils.collectives import psum_if_varying

    n = len(jax.devices())
    if args.seq_len % n:
        raise SystemExit(
            f"--seq-len must be divisible by the device count ({n})")
    mesh = jax.make_mesh((n,), ("context",))

    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers,
                    num_attention_heads=args.heads,
                    max_seq_len=args.seq_len, remat=True,
                    dtype=jnp.bfloat16,
                    context_axis="context" if n > 1 else None,
                    context_mechanism=args.mechanism)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(args.seed))
    adam = FusedAdam(lr=args.lr)
    opt_state = adam.init(params)

    seq_spec = P(None, "context")

    def local_step(params, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens,
                                                     targets)
        # varying leaves hold ring-partial sums; invariant ones were
        # auto-reduced — same staging as the DP layer
        return loss, psum_if_varying(grads, "context")

    @jax.jit
    def train_step(params, opt_state, tokens, targets):
        if n > 1:
            loss, grads = shard_map(
                local_step, mesh=mesh,
                in_specs=(P(), seq_spec, seq_spec),
                out_specs=(P(), P()))(params, tokens, targets)
        else:
            loss, grads = jax.value_and_grad(model.loss)(params, tokens,
                                                         targets)
        params, opt_state = adam.step(grads, params, opt_state)
        return params, opt_state, loss

    rng = np.random.RandomState(args.seed)

    def make_batch():
        t = rng.randint(0, args.vocab, (args.batch_size, args.seq_len))
        return jnp.asarray(t), jnp.asarray(
            rng.randint(0, args.vocab, (args.batch_size, args.seq_len)))

    tokens, targets = make_batch()
    params, opt_state, loss = train_step(params, opt_state, tokens,
                                         targets)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for step in range(1, args.steps + 1):
        tokens, targets = make_batch()
        params, opt_state, loss = train_step(params, opt_state, tokens,
                                             targets)
        if step % args.print_freq == 0 or step == args.steps:
            tok_s = step * args.batch_size * args.seq_len \
                / (time.perf_counter() - t0)
            print(f"step {step:4d}  loss {float(loss):8.4f}  "
                  f"{tok_s:10.0f} tok/s", flush=True)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    print(f"DONE mechanism={args.mechanism} devices={n} "
          f"global_seq={args.seq_len} "
          f"throughput={args.steps * args.batch_size * args.seq_len / dt:.0f}"
          " tok/s")


if __name__ == "__main__":
    main()
