#!/usr/bin/env python
"""7B-class GPT pretraining via TP x PP x DP (BASELINE.md row 2: "GPT
7B-class, tokens/sec/chip via tensor+pipeline parallel").

The model is the flagship :class:`apex_tpu.models.gpt.GPTModel` at
hidden=4096 / layers=32 / heads=32 / seq=2048 (~6.9B params with the
tied 50304 vocab); parallelism is the explicit shard_map form —
``pack_for_shard_map`` + the ring pipeline (``pipeline_step``, 1F1B on
a compiled scan) over a ``(data, pipe, model)`` mesh with sequence
parallelism on the TP axis — with per-layer remat and a FusedAdam
step, bf16 activations and fp32 params.

Pod launch (v5e-64 example; the same script, no code changes):

    # 16 hosts x 4 chips, multi-controller JAX: run on EVERY host
    python examples/gpt7b/pretrain_gpt7b.py --tp 4 --pp 4 --steps 100

    TP rides the intra-host ICI (tp=4 matches the v5e host's 2x2
    block); PP spans hosts (stage boundaries are the only inter-host
    hops, one (mb, s, h) ppermute per tick); the leftover mesh extent
    is DP.  Multi-controller init (jax.distributed.initialize) is
    automatic under TPU pod runtimes.

Hardware-free validation (what CI runs — same code path, scaled shapes):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
    python examples/gpt7b/pretrain_gpt7b.py --smoke --steps 2

``--smoke`` keeps the FULL topology (tp=2 x pp=2 x dp=2) and every
collective family, shrinking only the shape hyperparameters; the real
config stays the default so the recipe is the runnable artifact for the
7B row.
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description="apex_tpu 7B GPT TP x PP")
    p.add_argument("--tp", type=int, default=4,
                   help="tensor-parallel ways (intra-host ICI)")
    p.add_argument("--pp", type=int, default=4,
                   help="pipeline stages (inter-host axis on pods)")
    p.add_argument("--hidden", type=int, default=4096)
    p.add_argument("--layers", type=int, default=32)
    p.add_argument("--heads", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--vocab", type=int, default=50304)
    p.add_argument("--microbatches", type=int, default=4,
                   help="pipeline microbatches per step (per dp rank)")
    p.add_argument("--micro-batch-size", type=int, default=1)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=1.5e-4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="scale shapes down for the 8-virtual-device CPU "
                        "mesh; topology (tp x pp x dp) is unchanged")
    return p.parse_args()


def main():
    args = parse_args()
    if args.smoke:
        args.tp, args.pp = 2, 2
        args.hidden, args.layers, args.heads = 64, 4, 4
        args.seq_len, args.vocab = 32, 128
        args.microbatches, args.micro_batch_size = 2, 2

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import (GPTConfig, GPTModel,
                                     pack_for_shard_map, pipeline_step)
    from apex_tpu.utils.collectives import shard_map_compat as shard_map
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import parallel_state

    n = len(jax.devices())
    tp, pp = args.tp, args.pp
    if n % (tp * pp):
        raise SystemExit(f"device count {n} not divisible by tp*pp="
                         f"{tp * pp}")
    mesh = parallel_state.initialize_model_parallel(tp, pp)
    dp = parallel_state.get_data_parallel_world_size()

    cfg_kw = dict(vocab_size=args.vocab, hidden_size=args.hidden,
                  num_layers=args.layers, num_attention_heads=args.heads,
                  max_seq_len=args.seq_len, dtype=jnp.bfloat16,
                  remat=True)
    serial = GPTModel(GPTConfig(**cfg_kw))
    params = serial.init_params(jax.random.PRNGKey(args.seed))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))

    # the ring pipeline's TP composition requires sequence parallelism
    par = GPTModel(GPTConfig(tensor_parallel_size=tp,
                             axis_name="model" if tp > 1 else None,
                             sequence_parallel=tp > 1,
                             **cfg_kw))
    tensor_axis = "model" if tp > 1 else None
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
        par, params, n_stages=pp, tensor_axis=tensor_axis)
    del params                                   # packed owns the memory
    adam = FusedAdam(lr=args.lr)
    opt_state = adam.init(packed)

    M, mb, seq = args.microbatches, args.micro_batch_size, args.seq_len
    tokens_per_step = dp * M * mb * seq

    def grad_step(sp, tokens, targets):
        tk = tokens.reshape(M, mb, seq)
        tg = targets.reshape(M, mb, seq)
        # remat follows cfg.remat=True (per-layer stage checkpoint)
        loss, g = pipeline_step(par, local_fn(sp), tk, tg,
                                pipe_axis="pipe", data_axis="data")
        return loss, repack_fn(g)

    @jax.jit
    def train_step(packed, opt_state, tokens, targets):
        loss, grads = shard_map(
            grad_step, mesh=mesh,
            in_specs=(in_specs, P("data"), P("data")),
            out_specs=(P(), in_specs))(packed, tokens, targets)
        new_packed, new_opt = adam.step(grads, packed, opt_state)
        return loss, new_packed, new_opt

    rng = np.random.RandomState(args.seed)
    print(f"gpt7b: params={n_params / 1e9:.2f}B mesh=(dp={dp}, pp={pp}, "
          f"tp={tp}) devices={n} tokens/step={tokens_per_step}")

    def hard_sync(tree):
        # bench.py::_sync pattern — a 1-element device->host readback.
        # jax.block_until_ready can return before device work retires in
        # some remote-device environments (see BASELINE.md round-4
        # correction), which silently voids the timing below.
        leaf = jax.tree_util.tree_leaves(tree)[0]
        if leaf.is_fully_addressable:
            # index a single element (not ravel: that dispatches a
            # full-size reshape outside jit, transiently doubling the
            # leaf's HBM footprint)
            np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))
        else:
            # multi-host pod: shards on other hosts are not addressable
            # here — readback would raise; block_until_ready is the only
            # portable sync (its known weakness is a single-process
            # remote-device tunnel, which is never the pod case)
            jax.block_until_ready(tree)

    losses, t0 = [], None
    for step in range(args.steps):
        tokens = jnp.asarray(
            rng.randint(0, args.vocab, (dp * M * mb, seq)))
        targets = jnp.asarray(
            rng.randint(0, args.vocab, (dp * M * mb, seq)))
        loss, packed, opt_state = train_step(packed, opt_state, tokens,
                                             targets)
        losses.append(float(loss))
        if step == 0:
            hard_sync(packed)
            t0 = time.perf_counter()          # exclude compile
        print(f"step {step}: loss={losses[-1]:.4f}")
    hard_sync(packed)
    if args.steps > 1 and t0 is not None:
        dt = (time.perf_counter() - t0) / (args.steps - 1)
        per_chip = tokens_per_step / dt / n
        print(f"throughput: {tokens_per_step / dt:.1f} tokens/s "
              f"({per_chip:.1f} tokens/s/chip, step {dt * 1e3:.0f} ms)")
    assert all(np.isfinite(losses)), losses
    print("DONE")


if __name__ == "__main__":
    main()
