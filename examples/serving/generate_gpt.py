#!/usr/bin/env python
"""Continuous-batching GPT serving demo (``apex_tpu.inference``).

Builds a small randomly-initialized GPT, submits a mixed batch of
requests (different prompt lengths, budgets, sampling modes) to the
:class:`~apex_tpu.inference.InferenceEngine`, and streams them through
the KV-cache decode path: each request gets one prefill when a cache
slot frees up, then rides the single batched ``decode_step`` until it
finishes — no batch drain between requests.

Runs anywhere (CPU demo sizes by default; the decode attention lowers to
the Pallas single-query kernel on TPU):

    python examples/serving/generate_gpt.py --requests 6 --max-slots 2

The greedy responses printed are token-identical to decoding each
request alone — the engine invariant the test suite asserts.
"""

from __future__ import annotations

import argparse


def parse_args():
    p = argparse.ArgumentParser(description="apex_tpu serving demo")
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--max-seq", type=int, default=64)
    p.add_argument("--max-slots", type=int, default=2,
                   help="cache slots == max concurrent sequences")
    p.add_argument("--requests", type=int, default=6)
    p.add_argument("--max-new-tokens", type=int, default=12)
    p.add_argument("--cache-dtype", choices=["bf16", "f32"],
                   default="bf16")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; the last request additionally "
                        "samples top-k when > 0")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.inference import (InferenceEngine, Request,
                                    SamplingParams)
    from apex_tpu.models.gpt import GPTConfig, GPTModel

    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers,
                    num_attention_heads=args.heads,
                    max_seq_len=args.max_seq)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(args.seed))
    cache_dtype = (jnp.bfloat16 if args.cache_dtype == "bf16"
                   else jnp.float32)
    engine = InferenceEngine(model, params, max_slots=args.max_slots,
                             cache_dtype=cache_dtype)
    print(f"devices={len(jax.devices())} slots={args.max_slots} "
          f"cache_dtype={args.cache_dtype}")

    rng = np.random.RandomState(args.seed)
    sampling = (SamplingParams() if args.temperature == 0.0 else
                SamplingParams(temperature=args.temperature, top_k=16))
    for i in range(args.requests):
        prompt = [int(t) for t in
                  rng.randint(1, args.vocab, rng.randint(3, 17))]
        engine.submit(Request(
            request_id=i, prompt=prompt,
            max_new_tokens=args.max_new_tokens,
            sampling=sampling if i == args.requests - 1
            else SamplingParams(),
            seed=args.seed + i))

    for r in engine.run():
        print(f"request {r.request_id}: prompt[{len(r.prompt)}] -> "
              f"{r.tokens} ({r.finish_reason})")

    s = engine.metrics.summary()
    print(f"served {s['requests']} requests, {s['tokens']} tokens at "
          f"{s['tokens_per_s']:.1f} tok/s | ttft p50 "
          f"{s['ttft_p50_s'] * 1e3:.1f} ms | token latency p50 "
          f"{s['token_latency_p50_s'] * 1e3:.2f} ms | occupancy "
          f"{s['slot_occupancy_mean']:.2f}")
    print("DONE")


if __name__ == "__main__":
    main()
