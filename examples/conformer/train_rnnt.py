#!/usr/bin/env python
"""Conformer RNN-T training recipe — BASELINE workload 5 ("Conformer
RNN-T: apex.contrib.transducer + fused multihead attention").

Every compute block is a framework surface:

* encoder   — conv subsampling + conformer blocks built from
              ``contrib.multihead_attn.SelfMultiheadAttn``
              (``include_norm_add=True`` residual variant),
              ``FusedLayerNorm``-backed norms, and a conv module with
              NHWC depthwise conv + ``contrib.groupbn``-style BN math
* predictor — ``apex_tpu.RNN.LSTM`` (the deprecated-tier surface, used
              exactly where the reference workload uses an LSTM)
* joint     — ``contrib.transducer.TransducerJoint`` (fused broadcast
              add + ReLU)
* loss      — ``contrib.transducer.TransducerLoss`` (alpha-recursion
              RNN-T NLL)
* optimizer — ``FusedNovoGrad`` (the classic RNN-T recipe optimizer)

Synthetic log-mel features and token targets; reports utterances/s.

Run:  python examples/conformer/train_rnnt.py --steps 20
"""

from __future__ import annotations

import argparse
import time
import warnings

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description="apex_tpu conformer RNN-T")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--audio-len", type=int, default=200,
                   help="input frames (subsampled 4x by the stem)")
    p.add_argument("--target-len", type=int, default=20)
    p.add_argument("--n-mels", type=int, default=80)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--pred-hidden", type=int, default=256)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--print-freq", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp

    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
    from apex_tpu.contrib.transducer import TransducerJoint, TransducerLoss
    from apex_tpu.normalization import FusedLayerNorm
    from apex_tpu.optimizers import FusedNovoGrad
    from apex_tpu.RNN import LSTM

    H, nh, L = args.hidden, args.heads, args.layers
    key = jax.random.PRNGKey(args.seed)

    attn = SelfMultiheadAttn(H, nh, include_norm_add=True)
    ln = FusedLayerNorm(H)      # stateless config holder, shared
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        predictor = LSTM(H, args.pred_hidden)
    joint = TransducerJoint(relu=True)
    loss_mod = TransducerLoss()

    def winit(key, *shape):
        return (shape[0] ** -0.5) * jax.random.normal(key, shape,
                                                      jnp.float32)

    def init_params(key):
        ks = iter(jax.random.split(key, 8 * L + 8))
        p = {
            # conv subsampling stem: (B, T, mels) -> (B, T/4, H)
            "stem1": winit(next(ks), 4 * args.n_mels, H),
            "stem_b1": jnp.zeros((H,)),
            "layers": [],
            "pred_embed": winit(next(ks), args.vocab, H),
            "predictor": predictor.init_params(next(ks)),
            "enc_proj": winit(next(ks), H, H),
            "pred_proj": winit(next(ks), args.pred_hidden, H),
            "out_proj": winit(next(ks), H, args.vocab + 1),
            "out_b": jnp.zeros((args.vocab + 1,)),
        }
        for i in range(L):
            p["layers"].append({
                "ff1": {"w1": winit(next(ks), H, 4 * H),
                        "w2": winit(next(ks), 4 * H, H),
                        "ln": ln.init_params()},
                "attn": attn.init_params(next(ks)),
                "conv": {"pw1": winit(next(ks), H, 2 * H),
                         "dw": 0.1 * jax.random.normal(next(ks), (5, H)),
                         "pw2": winit(next(ks), H, H),
                         "ln": ln.init_params()},
                "ff2": {"w1": winit(next(ks), H, 4 * H),
                        "w2": winit(next(ks), 4 * H, H),
                        "ln": ln.init_params()},
            })
        return p

    def feed_forward(p, x):
        h = ln(p["ln"], x)
        h = jax.nn.silu(h @ p["w1"]) @ p["w2"]
        return x + 0.5 * h

    def conv_module(p, x):
        h = ln(p["ln"], x)
        h = h @ p["pw1"]                          # (B, T, 2H)
        a, b = jnp.split(h, 2, axis=-1)
        h = a * jax.nn.sigmoid(b)                 # GLU
        # depthwise conv over time (kernel 5): ONE grouped conv, not a
        # per-channel python loop (feature_group_count=H)
        kern = p["dw"][:, None, :]                       # (K, 1, H) = WIO
        h = jax.lax.conv_general_dilated(
            h, kern, window_strides=(1,), padding="SAME",
            dimension_numbers=("NWC", "WIO", "NWC"),
            feature_group_count=H)
        h = jax.nn.silu(h)
        return x + h @ p["pw2"]

    def encoder(p, feats):
        b, t, m = feats.shape
        t4 = t // 4
        x = feats[:, :t4 * 4].reshape(b, t4, 4 * m)
        x = jax.nn.relu(x @ p["stem1"] + p["stem_b1"])
        for lp in p["layers"]:
            x = feed_forward(lp["ff1"], x)
            # SelfMultiheadAttn is (seq, batch, hidden) with fused
            # residual+LN (include_norm_add)
            x = attn(lp["attn"], x.transpose(1, 0, 2),
                     is_training=False).transpose(1, 0, 2)
            x = conv_module(lp["conv"], x)
            x = feed_forward(lp["ff2"], x)
        return x                                   # (B, T/4, H)

    def forward_loss(p, feats, labels, f_len, y_len):
        enc = encoder(p, feats)                    # (B, T', H)
        # predictor consumes blank-prepended targets, time-major
        tokens = jnp.pad(labels, ((0, 0), (1, 0)))  # (B, U+1)
        emb = jnp.take(p["pred_embed"], tokens, axis=0)
        pred, _ = predictor.apply(p["predictor"], emb.transpose(1, 0, 2))
        pred = pred.transpose(1, 0, 2)             # (B, U+1, Hp)
        f = enc @ p["enc_proj"]
        g = pred @ p["pred_proj"]
        h = joint(f, g)                            # (B, T', U+1, H) +relu
        logits = h @ p["out_proj"] + p["out_b"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = loss_mod(logp, labels, f_len, y_len, blank_idx=0)
        return jnp.mean(nll)

    params = init_params(key)
    opt = FusedNovoGrad(lr=args.lr)
    opt_state = opt.init(params)

    @jax.jit
    def train_step(params, opt_state, feats, labels, f_len, y_len):
        loss, grads = jax.value_and_grad(forward_loss)(
            params, feats, labels, f_len, y_len)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    rng = np.random.RandomState(args.seed)
    t4 = args.audio_len // 4

    def make_batch():
        feats = jnp.asarray(rng.randn(args.batch_size, args.audio_len,
                                      args.n_mels), jnp.float32)
        labels = jnp.asarray(rng.randint(
            1, args.vocab, (args.batch_size, args.target_len)))
        f_len = jnp.asarray(rng.randint(t4 // 2, t4 + 1,
                                        (args.batch_size,)))
        y_len = jnp.asarray(rng.randint(args.target_len // 2,
                                        args.target_len + 1,
                                        (args.batch_size,)))
        return feats, labels, f_len, y_len

    batch = make_batch()
    params, opt_state, loss = train_step(params, opt_state, *batch)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for step in range(1, args.steps + 1):
        batch = make_batch()
        params, opt_state, loss = train_step(params, opt_state, *batch)
        if step % args.print_freq == 0 or step == args.steps:
            print(f"step {step:4d}  rnnt_loss {float(loss):9.4f}  "
                  f"{step * args.batch_size / (time.perf_counter() - t0):6.1f}"
                  " utt/s", flush=True)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    print(f"DONE layers={L} hidden={H} "
          f"throughput={args.steps * args.batch_size / dt:.1f} utt/s")


if __name__ == "__main__":
    main()
