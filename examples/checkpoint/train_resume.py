#!/usr/bin/env python
"""Checkpoint / resume recipe (SURVEY §5 "checkpoint/resume"): save the
COMPLETE training state mid-run — params, fused-optimizer state (packed
moment buckets + step counter), dynamic loss-scaler state, and the data
seed — restore it in a fresh process, and continue bit-for-bit.

The reference's apex-owned checkpoint surface is the amp loss-scaler
state_dict round-trip (apex ``tests/L0/run_amp/test_checkpointing.py``);
model/optimizer persistence is user-side ``torch.save``.  Here the whole
state is one pytree saved through the framework's own parallel-IO
runtime (:mod:`apex_tpu.contrib.gpu_direct_storage`, the cuFile-GDS
equivalent), so the recipe doubles as the failure-recovery story: kill
the process at any step, relaunch with ``--resume``, the trajectory is
identical to the uninterrupted run (the test asserts exactly that).

Run:  python examples/checkpoint/train_resume.py --steps 6 \\
          --save-at 3 --ckpt /tmp/ck.bin
      python examples/checkpoint/train_resume.py --steps 6 \\
          --resume --ckpt /tmp/ck.bin
"""

from __future__ import annotations

import argparse

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description="apex_tpu checkpoint/resume")
    p.add_argument("--steps", type=int, default=6,
                   help="total steps of the full trajectory")
    p.add_argument("--save-at", type=int, default=3,
                   help="step AFTER which the checkpoint is written")
    p.add_argument("--ckpt", type=str, default="/tmp/apex_tpu_ck.bin")
    p.add_argument("--resume", action="store_true",
                   help="restore --ckpt and run the remaining steps")
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()

    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.contrib import gpu_direct_storage as gds
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_attention_heads=4,
                    max_seq_len=args.seq_len)
    model = GPTModel(cfg)
    adam = FusedAdam(lr=args.lr)
    # fp16-style dynamic scaler: its state (scale + growth counter) is
    # part of the checkpoint contract, like apex amp.state_dict()
    scaler = amp.LossScaler(loss_scale="dynamic", init_scale=2.0 ** 12)

    def batch_for(step):
        """Deterministic per-step synthetic batch (seeded off the step,
        so a resumed run sees the same data stream)."""
        r = np.random.RandomState(args.seed * 100003 + step)
        t = jnp.asarray(r.randint(0, args.vocab,
                                  (4, args.seq_len)))
        return t, jnp.asarray(
            r.randint(0, args.vocab, (4, args.seq_len)))

    @jax.jit
    def train_step(params, opt_state, sstate, tokens, targets):
        def loss_fn(p):
            return amp.scale_loss(model.loss(p, tokens, targets), sstate)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state, sstate, _ = amp.unscale_step(
            adam, grads, params, opt_state, scaler, sstate)
        return loss / sstate.loss_scale, params, opt_state, sstate

    if args.resume:
        # the loader restores INTO a structure template (the pytree is
        # stored flat); building it from init is cheap and guarantees
        # the treedef matches what training would have produced
        params_t = model.init_params(jax.random.PRNGKey(args.seed))
        template = {"params": params_t, "opt": adam.init(params_t),
                    "scaler": tuple(scaler.init()),
                    "step": jnp.int32(0)}
        state = gds.load(args.ckpt, tree_like=template)
        params, opt_state = state["params"], state["opt"]
        sstate = amp.LossScaleState(*(jnp.asarray(v)
                                      for v in state["scaler"]))
        start = int(state["step"])
        print(f"resumed from {args.ckpt} at step {start}")
    else:
        params = model.init_params(jax.random.PRNGKey(args.seed))
        opt_state = adam.init(params)
        sstate = scaler.init()
        start = 0

    for step in range(start, args.steps):
        tokens, targets = batch_for(step)
        loss, params, opt_state, sstate = train_step(
            params, opt_state, sstate, tokens, targets)
        print(f"step {step}: loss={float(loss):.6f} "
              f"scale={float(sstate.loss_scale):.0f}")
        if not args.resume and step + 1 == args.save_at:
            gds.save(args.ckpt, {
                "params": params,
                "opt": opt_state,
                "scaler": tuple(sstate),
                "step": jnp.int32(step + 1),
            })
            print(f"checkpoint written to {args.ckpt} after step {step}")
    print("DONE")


if __name__ == "__main__":
    main()
