"""apex_tpu benchmark — run on the real TPU chip, print ONE JSON line.

Measures the binding BASELINE.md metrics that are measurable on a single
chip:

* BERT-large (340M) MLM pretrain step with FusedLAMB + amp O2 — the
  BASELINE.md row-1 north-star workload — -> tokens/s and MFU (>=50%
  MFU target at pod scale).  This is the headline metric.
* GPT (350M-class) fwd+bwd+FusedAdam step -> tokens/s and MFU.
  Attention is the Pallas flash kernel, so batch is no longer
  HBM-capped by materialized scores.
* FusedAdam packed-bucket step vs unfused optax adam on the same params
  -> speedup (the core premise of the multi-tensor engine), same
  paired-window median protocol.

Timing methodology (round-4 correction): ``jax.block_until_ready``
through the axon tunnel can return before device work retires — rounds
1-3 of this bench (and their MFU headlines of 0.7+) were built on it
and are VOID.  Every measurement here hard-synchronizes with a 1-element
device->host readback (:func:`_sync`), which cannot lie; the ~100 ms
readback round-trip is amortized over 8 timed iterations.  The MFU
headline remains the median over several paired passes — each pass
times a dependent-matmul calibration chain and the train step in the
same window and takes ``achieved / max(calibration, spec, achieved)``
— with the per-pass spread in the JSON, and at least one unclamped
pass is asserted.  Honest current numbers are ~0.2-0.3 MFU single-chip,
not the earlier phantom 0.8.
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# peak dense bf16 FLOPs/s per chip by device kind (public spec sheets)
_PEAK_BF16 = {
    "TPU v5 lite": 197e12,       # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,       # v6e / Trillium
    "TPU v6e": 918e12,
}


def _spec_peak() -> float:
    kind = jax.devices()[0].device_kind
    # longest matching prefix wins ("TPU v5 lite" before "TPU v5")
    best = 0.0
    best_len = -1
    for k, v in _PEAK_BF16.items():
        if kind.startswith(k) and len(k) > best_len:
            best, best_len = v, len(k)
    return best if best_len >= 0 else 197e12  # conservative default


_CAL_STATE = None


def _sync(x):
    """Hard synchronization: a 1-element device->host read of a leaf.

    ``block_until_ready`` through the axon tunnel can return before the
    device work retires (observed: 48 dependent 8192^3 matmuls
    "complete" in under a millisecond), which silently voids every
    timing built on it; a host readback cannot lie."""
    leaf = jax.tree_util.tree_leaves(x)[0]
    # single-element index, not ravel: outside jit a ravel dispatches a
    # full-size reshape program with a fresh output buffer, transiently
    # doubling the leaf's HBM footprint
    np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))
    return x


_CAL_CHAIN = 96      # 4096^3 matmuls: ~100 ms device work per dispatch


def _calibrated_peak(rounds: int = 1) -> float:
    """Sustained bf16 matmul FLOP/s on this device — ONE timing window
    per call so callers can pair it tightly with another measurement.

    The probe is a chain of ``_CAL_CHAIN`` DEPENDENT matmuls
    inside one jitted program (~100 ms of device work per dispatch):
    per-dispatch tunnel latency must be amortized the way a real train
    step amortizes it, otherwise the calibration undershoots large
    steps by whole multiples and the MFU guard trips.  The chain CARRIES
    its operand between calls (donated, like the train step's params) so
    every timed execution is a distinct computation — repeated identical
    executions through the tunnel return implausibly fast.  State is
    built once and cached (re-jitting per call would widen the very
    window gap the pairing exists to close)."""
    global _CAL_STATE
    # 4096^2 operands: big enough for full MXU utilization, small enough
    # (3 x 32 MB) to coexist with a batch-32 model's HBM footprint
    n = 4096
    if _CAL_STATE is None:
        key = jax.random.PRNGKey(0)
        a = jax.random.normal(key, (n, n), jnp.bfloat16)
        b = jax.random.normal(key, (n, n), jnp.bfloat16)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def chain(a, b):
            def body(c, _):
                # dependent chain: no dead-code elimination, no overlap;
                # the rescale keeps values finite across calls
                c = jnp.dot(c, b, preferred_element_type=jnp.bfloat16)
                c = c * (1.0 / jnp.maximum(
                    jnp.max(jnp.abs(c)), 1.0)).astype(jnp.bfloat16)
                return c, None
            c, _ = jax.lax.scan(body, a, None, length=_CAL_CHAIN)
            return c

        a = _sync(chain(a, b))                   # compile outside timing
        _CAL_STATE = {"a": a, "b": b, "chain": chain}
    st = _CAL_STATE
    best = 0.0
    for _ in range(rounds):
        iters = 2
        t0 = time.perf_counter()
        for _ in range(iters):
            st["a"] = st["chain"](st["a"], st["b"])
        _sync(st["a"])
        dt = (time.perf_counter() - t0) / (iters * _CAL_CHAIN)
        best = max(best, 2.0 * n ** 3 / dt)
    return best


def _time_steps(fn, args, warmup=2, iters=8, rounds=3):
    """Median over ``rounds`` timing rounds (tunnel timing is noisy);
    hard-synced via a host readback (see :func:`_sync`)."""
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _sync(out)
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    return times[len(times) // 2]


def _paired_mfu_passes(run, args, tokens_per_step, flops_per_token,
                       n_passes=5):
    """The paired-calibration MFU protocol shared by the model legs:
    each pass times a bf16 calibration matmul and the train step
    back-to-back in one window; the headline is the median unclamped
    pass (see module docstring)."""
    spec = _spec_peak()
    passes = []
    for _ in range(n_passes):
        cal = max(_calibrated_peak(rounds=1), spec)
        dt = _time_steps(run, args, warmup=1, rounds=1)
        achieved = tokens_per_step / dt * flops_per_token
        peak = max(cal, achieved)
        passes.append({"dt": dt, "achieved": achieved, "cal": cal,
                       "peak": peak, "mfu": achieved / peak})
    # a pass whose step outran its calibration (mfu clamped to 1.0) is a
    # calibration undershoot, not evidence; the headline comes from the
    # unclamped passes, and at least one must exist — all-clamped means
    # the calibration matmul itself is broken, which clamping would
    # otherwise silently convert into a perfect score
    clean = [p for p in passes if p["achieved"] <= p["cal"]]
    assert clean, (
        "every calibration pass undershot the step "
        f"(achieved/cal spread {[round(p['achieved'] / p['cal'], 3) for p in passes]}) "
        "— calibration matmul is not measuring peak")
    clean.sort(key=lambda p: p["mfu"])
    mid = clean[len(clean) // 2]
    mfu = mid["mfu"]
    assert mfu > 0.0, f"non-positive MFU {mfu}"
    return {
        "mfu_pass_spread": [round(p["mfu"], 4) for p in passes],
        "step_time_s": mid["dt"],
        "tokens_per_s": tokens_per_step / mid["dt"],
        "achieved_flops": mid["achieved"],
        "peak_spec": spec,
        "peak_calibrated": mid["cal"],
        "peak_used": mid["peak"],
        "peak_source": ("calibrated_matmul" if mid["peak"] == mid["cal"]
                        else "achieved_step (matmul calibration undershot)"),
        "mfu_spec": mid["achieved"] / spec,
        "mfu": mfu,
    }


def bench_gpt_train_step():
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    # measured best config on v5e (hard-synced sweep): the fused
    # logit-free LM head removes the (b*s, vocab) logits from HBM (the
    # materialized head OOMs at batch 24), which buys enough headroom
    # for SELECTIVE remat at batch 16 — faster than full remat at batch
    # 32 (25.5 vs 23.6 Ktok/s) because the backward skips the GEMM
    # recompute
    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_attention_heads=16, max_seq_len=1024, remat=True,
                    remat_policy="dots", dtype=jnp.bfloat16)
    batch, seq = 16, 1024
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    adam = FusedAdam(lr=1e-4)
    opt_state = adam.init(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    # donation (params + opt state reuse their buffers) and per-layer
    # remat keep the 350M config inside a single chip's HBM at batch 16
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens,
                                                     targets)
        new_params, new_opt = adam.step(grads, params, opt_state)
        return loss, new_params, new_opt

    def run(tokens, targets):
        nonlocal params, opt_state
        loss, params, opt_state = train_step(params, opt_state, tokens,
                                             targets)
        return loss

    # PaLM-style accounting: 6*N per token (fwd+bwd) + attention term
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size \
        * seq
    out = _paired_mfu_passes(run, (tokens, targets), batch * seq,
                             flops_per_token)
    return {"n_params": n_params, "batch": batch, "seq": seq, **out}


def bench_bert_lamb_train_step():
    """BASELINE.md row 1 — the binding north-star workload: BERT-large
    MLM pretrain step with FusedLAMB + MixedFusedLayerNorm + amp O2
    entrypoints (bf16 model params, fp32 masters in the optimizer,
    keep-norm-fp32)."""
    from apex_tpu import amp
    from apex_tpu.models.bert import BertConfig, BertModel
    from apex_tpu.optimizers import FusedLAMB

    # full remat: BERT at batch 32 x seq 512 cannot afford the "dots"
    # policy's saved GEMM outputs (~7 GB) on top of the LAMB masters
    cfg = BertConfig(hidden_size=1024, num_layers=24,
                     num_attention_heads=16, max_seq_len=512, remat=True,
                     dtype=jnp.bfloat16)
    batch, seq = 32, 512
    model = BertModel(cfg)
    lamb = FusedLAMB(lr=1e-3)
    state = amp.initialize(model.loss, lamb, opt_level="O2")
    params = state.cast_params(model.init_params(jax.random.PRNGKey(0)))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    opt_state = lamb.init(params)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    # MLM convention: label = original id at ~15% masked positions, -1 off
    labels = np.where(rng.rand(batch, seq) < 0.15,
                      rng.randint(0, cfg.vocab_size, (batch, seq)), -1)
    labels = jnp.asarray(labels)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(state.apply_fn)(params, tokens,
                                                         labels)
        new_params, new_opt = lamb.step(grads, params, opt_state)
        return loss, new_params, new_opt

    def run(tokens, labels):
        nonlocal params, opt_state
        loss, params, opt_state = train_step(params, opt_state, tokens,
                                             labels)
        return loss

    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size \
        * seq
    out = _paired_mfu_passes(run, (tokens, labels), batch * seq,
                             flops_per_token)
    return {"n_params": n_params, "batch": batch, "seq": seq, **out}


def bench_fused_adam_vs_optax():
    import optax

    from apex_tpu.optimizers import FusedAdam

    # this leg is a self-relative ratio — the calibration buffers from
    # the model legs are dead weight; free them before allocating ~9 GB
    # of optimizer state
    global _CAL_STATE
    _CAL_STATE = None

    rng = np.random.RandomState(1)
    shapes = []
    # BERT-large-ish param census: many embeddings/matrices/vectors
    for _ in range(24):
        shapes += [(1024, 1024), (4096, 1024), (1024, 4096),
                   (1024,), (4096,), (1024,), (1024,)]
    shapes += [(30522, 1024), (512, 1024)]
    params = [jnp.asarray(rng.randn(*s).astype(np.float32) * 0.02)
              for s in shapes]
    grads = [jnp.asarray(rng.randn(*s).astype(np.float32) * 1e-3)
             for s in shapes]

    fused = FusedAdam(lr=1e-3)
    fstate = fused.init(params)

    @jax.jit
    def fused_step(grads, params, state):
        return fused.step(grads, params, state)

    opt = optax.adam(1e-3)
    ostate = opt.init(params)

    @jax.jit
    def optax_step(grads, params, state):
        updates, new_state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state

    # The tunnel's absolute timing drifts between windows (observed
    # 1.6x..3x swings for this leg across rounds), so — like the MFU
    # leg — each pass times both sides back-to-back in one window and
    # the headline is the median per-pass ratio, with the spread shipped.
    #
    # Caveat on the ratio's meaning: this microbenchmark hands the step
    # PRE-MATERIALIZED grads, so the bucket packing is a pure extra HBM
    # round trip here; inside a real jitted train step XLA fuses the
    # packing into the gradient producers. The standalone ratio is the
    # WORST case for the packed engine (honest round-4 value ~0.4x, i.e.
    # slower than per-leaf optax — the apex launch-overhead rationale
    # does not exist on TPU; the packed layout's remaining wins are the
    # ZeRO collectives and state layout).
    passes = []
    for _ in range(5):
        t_fused = _time_steps(fused_step, (grads, params, fstate),
                              warmup=1, rounds=1)
        t_optax = _time_steps(optax_step, (grads, params, ostate),
                              warmup=1, rounds=1)
        passes.append({"fused": t_fused, "optax": t_optax,
                       "speedup": t_optax / t_fused})
    passes.sort(key=lambda p: p["speedup"])
    mid = passes[len(passes) // 2]

    # fp16 leg: Mosaic has no f16, so fp16 buckets take the documented
    # jnp fallback (ops/multi_tensor.py::_use_kernel) — quantify what
    # that path costs relative to the f32 Pallas path on the same
    # element count (VERDICT r3 weak item 4: "nothing in BENCH
    # quantifies that path")
    # same optimizer configuration on both sides — the ratio must
    # isolate kernel-vs-fallback, not master-weights bookkeeping.
    # The optax comparison state is no longer needed: free it before
    # allocating the fp16 set.
    del ostate
    params16 = [p.astype(jnp.float16) for p in params]
    grads16 = [g.astype(jnp.float16) for g in grads]
    fused16 = FusedAdam(lr=1e-3)
    fstate16 = fused16.init(params16)

    @jax.jit
    def fused16_step(grads, params, state):
        return fused16.step(grads, params, state)

    fp16_passes = []
    for _ in range(3):
        t16 = _time_steps(fused16_step, (grads16, params16, fstate16),
                          warmup=1, rounds=1)
        t32 = _time_steps(fused_step, (grads, params, fstate),
                          warmup=1, rounds=1)
        fp16_passes.append(t16 / t32)
    fp16_passes.sort()

    return {
        "n_tensors": len(shapes),
        "n_elements": int(sum(int(np.prod(s)) for s in shapes)),
        "fused_step_s": mid["fused"],
        "optax_step_s": mid["optax"],
        "speedup": mid["speedup"],
        "spread": [round(p["speedup"], 3) for p in passes],
        "fp16_fallback_vs_f32_kernel": round(
            fp16_passes[len(fp16_passes) // 2], 3),
        "fp16_fallback_spread": [round(r, 3) for r in fp16_passes],
    }


def main():
    backend = jax.default_backend()
    bert = bench_bert_lamb_train_step()
    gpt = bench_gpt_train_step()
    adam = bench_fused_adam_vs_optax()
    rounded = lambda d: {k: (round(v, 6) if isinstance(v, float) else v)
                         for k, v in d.items()}
    # headline = the binding BASELINE.md row-1 workload (BERT-large +
    # FusedLAMB + amp O2); the GPT and optimizer legs ride in `extra`
    result = {
        "metric": "bert_large_lamb_mfu",
        "value": round(bert["mfu"], 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(bert["mfu"] / 0.5, 4),  # >=50% MFU target
        "extra": {
            "backend": backend,
            "device_kind": jax.devices()[0].device_kind,
            "bert_large_lamb": rounded(bert),
            "gpt_350m_train_mfu": round(gpt["mfu"], 4),
            "gpt": rounded(gpt),
            "fused_adam_vs_optax": rounded(adam),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
