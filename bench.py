"""apex_tpu benchmark — run on the real TPU chip, print ONE JSON line.

Measures the binding BASELINE.md metrics that are measurable on a single
chip:

* BERT-large (340M) MLM pretrain step with FusedLAMB + amp O2 — the
  BASELINE.md row-1 north-star workload — -> tokens/s and MFU (>=50%
  MFU target at pod scale).  This is the headline metric.  Round-5
  config (measured sweep, tools/profile_bert.py): micro-batch 16 x 2
  gradient accumulation (global batch 32), NO remat, per-leaf
  (bucketed=False) FusedLAMB.
* GPT (350M-class) fwd+bwd+FusedAdam step -> tokens/s and MFU
  (batch 8, no remat, per-leaf FusedAdam).
* A per-component breakdown of the BERT step (attention / GEMMs / FFN /
  LN / LM head / optimizer), each isolated with in-jit chaining so the
  ~5-8 ms per-dispatch tunnel cost cannot pollute small components.
* The optimizer question, settled two ways: (a) standalone packed
  FusedAdam vs per-leaf FusedAdam vs unfused optax on the same param
  census; (b) IN-STEP: the same BERT train step with packed vs
  per-leaf FusedLAMB vs an optax LAMB + f32 masters.

Timing methodology (round-4 correction): ``jax.block_until_ready``
through the axon tunnel can return before device work retires — rounds
1-3 of this bench (and their MFU headlines of 0.7+) were built on it
and are VOID.  Every measurement here hard-synchronizes with a 1-element
device->host readback (:func:`_sync`, mirrored in tools/_timing.py),
amortized over >=8 timed iterations.

Peak accounting (round-5 correction): the calibrated peak is reported
RAW.  This device sustains only ~100 TF/s bf16 and ~350 GB/s HBM
(~51% / ~43% of the v5e spec sheet) on chained dependent 4096^3
matmuls / 1 GB axpy probes, so the spec-sheet MFU (the headline, kept
for BASELINE comparability) is capped near 0.51 on this part no matter
how good the program is; ``mfu_vs_calibrated`` states utilization of
the silicon as delivered.  Round 4 clamped the calibration UP to spec,
which hid this ceiling.
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# peak dense bf16 FLOPs/s per chip by device kind (public spec sheets)
_PEAK_BF16 = {
    "TPU v5 lite": 197e12,       # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,       # v6e / Trillium
    "TPU v6e": 918e12,
}


def _spec_peak() -> float:
    kind = jax.devices()[0].device_kind
    # longest matching prefix wins ("TPU v5 lite" before "TPU v5")
    best = 0.0
    best_len = -1
    for k, v in _PEAK_BF16.items():
        if kind.startswith(k) and len(k) > best_len:
            best, best_len = v, len(k)
    return best if best_len >= 0 else 197e12  # conservative default


_CAL_STATE = None


def _sync(x):
    """Hard synchronization: a 1-element device->host read of a leaf.

    ``block_until_ready`` through the axon tunnel can return before the
    device work retires (observed: 48 dependent 8192^3 matmuls
    "complete" in under a millisecond), which silently voids every
    timing built on it; a host readback cannot lie.  Single-element
    index, not ravel: an out-of-jit ravel dispatches a full-size
    reshape, transiently doubling the leaf's HBM footprint.
    (Kept in sync with tools/_timing.py::sync — bench.py stays
    standalone by driver contract.)"""
    leaf = jax.tree_util.tree_leaves(x)[0]
    np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))
    return x


_CAL_CHAIN = 96      # 4096^3 matmuls: ~100 ms device work per dispatch


def _calibrated_peak(rounds: int = 1) -> float:
    """Sustained bf16 matmul FLOP/s on this device — ONE timing window
    per call so callers can pair it tightly with another measurement.

    The probe is a chain of ``_CAL_CHAIN`` DEPENDENT matmuls
    inside one jitted program (~100 ms of device work per dispatch):
    per-dispatch tunnel latency must be amortized the way a real train
    step amortizes it.  The chain CARRIES its operand between calls
    (donated) so every timed execution is a distinct computation —
    repeated identical executions through the tunnel return implausibly
    fast.  Returned RAW: on this device it lands around 100 TF/s, half
    the 197 TF/s spec sheet (round-5 finding) — do NOT clamp it up."""
    global _CAL_STATE
    # 4096^2 operands: big enough for full MXU utilization, small enough
    # (3 x 32 MB) to coexist with a batch-32 model's HBM footprint
    n = 4096
    if _CAL_STATE is None:
        key = jax.random.PRNGKey(0)
        a = jax.random.normal(key, (n, n), jnp.bfloat16)
        b = jax.random.normal(key, (n, n), jnp.bfloat16)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def chain(a, b):
            def body(c, _):
                # dependent chain: no dead-code elimination, no overlap;
                # the rescale keeps values finite across calls
                c = jnp.dot(c, b, preferred_element_type=jnp.bfloat16)
                c = c * (1.0 / jnp.maximum(
                    jnp.max(jnp.abs(c)), 1.0)).astype(jnp.bfloat16)
                return c, None
            c, _ = jax.lax.scan(body, a, None, length=_CAL_CHAIN)
            return c

        a = _sync(chain(a, b))                   # compile outside timing
        _CAL_STATE = {"a": a, "b": b, "chain": chain}
    st = _CAL_STATE
    best = 0.0
    for _ in range(rounds):
        iters = 2
        t0 = time.perf_counter()
        for _ in range(iters):
            st["a"] = st["chain"](st["a"], st["b"])
        _sync(st["a"])
        dt = (time.perf_counter() - t0) / (iters * _CAL_CHAIN)
        best = max(best, 2.0 * n ** 3 / dt)
    return best


def _free_calibration():
    global _CAL_STATE
    _CAL_STATE = None


def _retry(fn, attempts=2):
    """The axon remote-compile tunnel drops ~5-10% of large compiles
    ('response body closed before all bytes were read'); one retry
    recompiles from the cache warm.  Returns None if every attempt
    fails — legs degrade to partial results rather than killing the
    whole bench run."""
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:                     # noqa: BLE001
            err = f"{type(e).__name__}: {str(e).splitlines()[0][:200]}"
            if i == attempts - 1:
                print(f"# bench leg failed after {attempts} attempts: "
                      f"{err}", flush=True)
    return None


def _time_steps(fn, args, warmup=2, iters=8, rounds=3):
    """Median over ``rounds`` timing rounds (tunnel timing is noisy);
    hard-synced via a host readback (see :func:`_sync`)."""
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _sync(out)
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    return times[len(times) // 2]


def _paired_mfu_passes(run, args, tokens_per_step, flops_per_token,
                       n_passes=5):
    """The paired-calibration MFU protocol shared by the model legs:
    each pass times a bf16 calibration matmul and the train step
    back-to-back in one window; the headline is the median pass.

    ``mfu`` (headline) is achieved/spec for BASELINE comparability;
    ``mfu_vs_calibrated`` divides by the RAW same-window calibration
    (clamped only by achieved itself: a step genuinely cannot beat a
    peak, so achieved > cal means the calibration undershot)."""
    spec = _spec_peak()
    passes = []
    for _ in range(n_passes):
        cal = _calibrated_peak(rounds=1)
        # a broken calibration (freed state, early tunnel return) lands
        # far below any plausible silicon; without this floor it would
        # silently clamp mfu_vs_calibrated to a fabricated 1.0
        assert cal > 0.2 * spec, (
            f"calibration probe measured {cal / 1e12:.1f} TF/s "
            f"(< 20% of the {spec / 1e12:.0f} TF/s spec) — the "
            "calibration matmul is not measuring peak")
        dt = _time_steps(run, args, warmup=1, rounds=1)
        achieved = tokens_per_step / dt * flops_per_token
        passes.append({"dt": dt, "achieved": achieved, "cal": cal,
                       "mfu_spec": achieved / spec,
                       "mfu_cal": achieved / max(cal, achieved)})
    passes.sort(key=lambda p: p["mfu_spec"])
    mid = passes[len(passes) // 2]
    assert mid["mfu_spec"] > 0.0
    return {
        "clamped_passes": sum(p["achieved"] > p["cal"] for p in passes),
        "mfu_pass_spread": [round(p["mfu_spec"], 4) for p in passes],
        "step_time_s": mid["dt"],
        "tokens_per_s": tokens_per_step / mid["dt"],
        "achieved_flops": mid["achieved"],
        "peak_spec": spec,
        "peak_calibrated_raw": mid["cal"],
        "silicon_fraction_of_spec": mid["cal"] / spec,
        "mfu_spec": mid["mfu_spec"],
        "mfu_vs_calibrated": mid["mfu_cal"],
        "mfu": mid["mfu_spec"],
    }


# ---------------------------------------------------------------------------
# model legs
# ---------------------------------------------------------------------------

def _accumulated_grads(loss_fn, params, tokens, labels, accum,
                       grad_dtype=None):
    """Mean loss + mean grads over ``accum`` leading-axis microbatches
    via lax.scan, accumulating in f32; ``grad_dtype`` casts the final
    grads (bf16 under O2 — the cotangent dtype the optimizer expects).
    Single source for the BERT and GPT accumulation legs (and imported
    by tools/sweep_gpt.py) so the accumulation numerics cannot drift
    between them."""
    if accum == 1:
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens[0],
                                                  labels[0])
        # same cotangent dtype contract as the accumulated branch: the
        # optimizer must see identical grad dtypes whatever accum is
        if grad_dtype is not None:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(grad_dtype), grads)
        return loss, grads

    def mb(carry, tl):
        tk, lb = tl
        l, g = jax.value_and_grad(loss_fn)(params, tk, lb)
        acc_l, acc_g = carry
        g = jax.tree_util.tree_map(
            lambda a, b: a + b.astype(jnp.float32), acc_g, g)
        return (acc_l + l, g), None

    zero = (jnp.zeros(()),
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params))
    (loss, grads), _ = jax.lax.scan(mb, zero, (tokens, labels))
    inv = 1.0 / accum
    cast = (lambda g: g * inv) if grad_dtype is None else (
        lambda g: (g * inv).astype(grad_dtype))
    return loss * inv, jax.tree_util.tree_map(cast, grads)

def _packed_opt(cls, **kw):
    """Packed-engine instance for the comparison arms.  The ctor opt-in
    was removed after the packed layout lost two bench rounds
    (packed_vs_optax_speedup 0.49-0.53); these arms keep measuring the
    engine — it survives as the ZeRO sharding unit — by flipping the
    attribute the way the distributed mixin selects it."""
    opt = cls(**kw)
    opt.bucketed = True
    return opt


def _make_bert_lamb_step(batch, accum, *, remat, bucketed, optimizer="lamb"):
    """The BASELINE row-1 workload: BERT-large MLM + FusedLAMB + amp O2
    (bf16 model params, fp32 masters, keep-norm-fp32), global batch
    ``batch * accum`` via in-step gradient accumulation."""
    from apex_tpu import amp
    from apex_tpu.models.bert import BertConfig, BertModel
    from apex_tpu.optimizers import FusedLAMB

    cfg = BertConfig(hidden_size=1024, num_layers=24,
                     num_attention_heads=16, max_seq_len=512, remat=remat,
                     remat_policy="dots" if remat else "full",
                     dtype=jnp.bfloat16)
    seq = 512
    model = BertModel(cfg)
    if optimizer == "lamb":
        opt = (_packed_opt(FusedLAMB, lr=1e-3) if bucketed
               else FusedLAMB(lr=1e-3, bucketed=False))
        # amp.initialize implements O2's fp32-master contract by setting
        # master_weights on THIS instance — it must be the optimizer
        # actually stepped, or the workload silently loses its masters
        state = amp.initialize(model.loss, opt, opt_level="O2")
    else:                                        # optax comparison arm
        import optax
        opt = optax.lamb(1e-3, b1=0.9, b2=0.999, eps=1e-6,
                         weight_decay=0.01)
        # the optax arm implements the same master contract explicitly
        # below; initialize only supplies apply_fn/cast_params here
        state = amp.initialize(model.loss, None, opt_level="O2")
    params = state.cast_params(model.init_params(jax.random.PRNGKey(0)))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    if optimizer == "lamb":
        opt_state = opt.init(params)
    else:
        # optax arm: the ONLY persistent state is (f32 masters, optax
        # state) — model-dtype params are derived inside the step.
        # Holding a separate params tree would alias its f32 norm
        # leaves with the masters (astype is an identity there) and a
        # donated call would then donate one buffer twice.
        masters = jax.tree_util.tree_map(
            lambda p: jnp.array(p, dtype=jnp.float32, copy=True), params)
        opt_state = (masters, opt.init(masters))
        dtype_template = jax.tree_util.tree_map(lambda p: p.dtype, params)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                     (accum, batch, seq)))
    # MLM convention: label = original id at ~15% masked positions, -1 off
    labels = np.where(rng.rand(accum, batch, seq) < 0.15,
                      rng.randint(0, cfg.vocab_size, (accum, batch, seq)),
                      -1)
    labels = jnp.asarray(labels)

    def grads_of(params, tokens, labels):
        return _accumulated_grads(state.apply_fn, params, tokens, labels,
                                  accum, grad_dtype=jnp.bfloat16)

    if optimizer == "lamb":
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, tokens, labels):
            loss, grads = grads_of(params, tokens, labels)
            new_params, new_opt = opt.step(grads, params, opt_state)
            return loss, new_params, new_opt
    else:
        import optax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def train_step(opt_state, tokens, labels):
            masters, ostate = opt_state
            model_params = jax.tree_util.tree_map(
                lambda m, dt: m.astype(dt), masters, dtype_template)
            loss, grads = grads_of(model_params, tokens, labels)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            updates, ostate = opt.update(grads, ostate, masters)
            masters = optax.apply_updates(masters, updates)
            return loss, (masters, ostate)

    if optimizer == "lamb":
        holder = {"p": params, "o": opt_state}

        def run(tokens, labels):
            loss, holder["p"], holder["o"] = train_step(
                holder["p"], holder["o"], tokens, labels)
            return loss
    else:
        holder = {"o": opt_state}

        def run(tokens, labels):
            loss, holder["o"] = train_step(holder["o"], tokens, labels)
            return loss

    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size \
        * seq
    return run, (tokens, labels), batch * accum * seq, flops_per_token, \
        n_params


def bench_bert_lamb_train_step():
    """Headline: measured-best config from the round-5 sweep — micro 16
    x 2 accumulation (global batch 32, same as rounds 1-4), NO remat
    (the per-leaf optimizer freed the packed-engine HBM that forced
    remat), per-leaf FusedLAMB."""
    run, args, tokens_per_step, flops_per_token, n_params = \
        _make_bert_lamb_step(16, 2, remat=False, bucketed=False)
    out = _paired_mfu_passes(run, args, tokens_per_step, flops_per_token)
    return {"n_params": n_params, "batch": 16, "accum": 2, "seq": 512,
            "remat": "none", "optimizer_layout": "per_leaf", **out}


def bench_lamb_in_step():
    """VERDICT r4 item 3: the SAME BERT train step with (a) packed
    FusedLAMB, (b) per-leaf FusedLAMB, (c) unfused optax LAMB + f32
    masters — the in-graph optimizer cost, where XLA may fuse packing
    into producers.  Small arm (batch 8, no remat, accum 1) keeps three
    full-model compiles affordable; the optimizer cost is constant per
    step so the DELTAS transfer to any batch."""
    arms = {}
    for name, kw in (("packed", dict(bucketed=True)),
                     ("per_leaf", dict(bucketed=False)),
                     ("optax_lamb", dict(bucketed=False,
                                         optimizer="optax"))):
        def arm():
            run, args, _, _, _ = _make_bert_lamb_step(8, 1, remat=False,
                                                      **kw)
            return _time_steps(run, args, warmup=1, iters=4, rounds=3)
        arms[name] = _retry(arm)
        jax.clear_caches()
    out = {"step_time_s": {k: (round(v, 5) if v else None)
                           for k, v in arms.items()}}
    if arms["packed"] and arms["per_leaf"]:
        out["per_leaf_vs_packed_speedup"] = round(
            arms["packed"] / arms["per_leaf"], 3)
    if arms["optax_lamb"] and arms["per_leaf"]:
        out["per_leaf_vs_optax_speedup"] = round(
            arms["optax_lamb"] / arms["per_leaf"], 3)
    return out


def bench_gpt_train_step():
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    # measured best (tools/sweep_gpt.py): micro-batch 8 x 2 gradient
    # accumulation (global batch 16, the same 16 Ktok/step as rounds
    # 1-4), NO remat, per-leaf FusedAdam; the fused logit-free LM head
    # keeps the (b*s, vocab) logits out of HBM, which is what lets
    # no-remat fit at all
    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_attention_heads=16, max_seq_len=1024, remat=False,
                    dtype=jnp.bfloat16)
    batch, seq, accum = 8, 1024, 2
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    adam = FusedAdam(lr=1e-4, bucketed=False)
    opt_state = adam.init(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                     (accum, batch, seq)))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                      (accum, batch, seq)))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens, targets):
        loss, grads = _accumulated_grads(model.loss, params, tokens,
                                         targets, accum)
        new_params, new_opt = adam.step(grads, params, opt_state)
        return loss, new_params, new_opt

    holder = {"p": params, "o": opt_state}

    def run(tokens, targets):
        loss, holder["p"], holder["o"] = train_step(holder["p"],
                                                    holder["o"], tokens,
                                                    targets)
        return loss

    # PaLM-style accounting: 6*N per token (fwd+bwd) + attention term
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size \
        * seq
    out = _paired_mfu_passes(run, (tokens, targets),
                             accum * batch * seq, flops_per_token)
    return {"n_params": n_params, "batch": batch, "accum": accum,
            "seq": seq, "remat": "none", "optimizer_layout": "per_leaf",
            **out}


def bench_gpt_decode():
    """Serving leg: prefill latency + steady-state batched decode
    throughput on the GPT-350M config with a bf16 KV cache.

    Decode is measured over the FULL slot table at mid-sequence depth —
    the continuous-batching engine's steady state, where every step is
    one `decode_step` whose batch dimension is the slot ring.  BASELINE
    has no inference row, so this rides in `extra` (the serving targets
    live in README "Inference & serving")."""
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.utils.platform import is_tpu_backend

    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_attention_heads=16, max_seq_len=1024,
                    dtype=jnp.bfloat16)
    slots, prompt_len = 8, 512
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)

    prefill = jax.jit(model.prefill)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (1, prompt_len)))
    t_prefill = _time_steps(lambda t: prefill(params, t)[0], (prompt,),
                            warmup=2, iters=4, rounds=3)

    cache = jnp.zeros((slots, cfg.num_layers, 2, cfg.max_seq_len,
                       cfg.num_attention_heads, cfg.head_dim),
                      jnp.bfloat16)
    positions = jnp.full((slots,), prompt_len, jnp.int32)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (slots,)))
    # the cache threads step-to-step; donate it on TPU so XLA writes in
    # place (donating on CPU only emits warnings)
    step = jax.jit(model.decode_step,
                   donate_argnums=(2,) if is_tpu_backend() else ())
    holder = {"c": cache}

    def run(tokens, positions):
        logits, holder["c"] = step(params, tokens, holder["c"], positions)
        return logits

    dt = _time_steps(run, (tokens, positions), warmup=2, iters=16,
                     rounds=3)
    return {"slots": slots, "prompt_len": prompt_len,
            "max_seq": cfg.max_seq_len, "cache_dtype": "bfloat16",
            "prefill_s": t_prefill,
            "prefill_tokens_per_s": prompt_len / t_prefill,
            "decode_step_s": dt,
            "decode_tokens_per_s": slots / dt,
            "decode_token_latency_ms": dt * 1e3}


# ---------------------------------------------------------------------------
# breakdown leg (VERDICT r4 item 1)
# ---------------------------------------------------------------------------

def bench_bert_breakdown():
    """Per-component times at the HEADLINE step's shapes — batch 16 x
    seq 512, x2 accumulation microbatches per step (the optimizer runs
    once per step, after accumulation, so it is NOT doubled) — each
    isolated and repeated inside ONE jitted scan so the ~5-8 ms
    per-dispatch tunnel cost cannot dominate a small op.  Sum of
    components ~= the un-rematted headline step; this names where the
    step's time goes (bench extra ``breakdown``)."""
    from apex_tpu.normalization import MixedFusedLayerNorm
    from apex_tpu.ops.flash_attention import flash_attention
    from apex_tpu.ops.lm_head import fused_linear_cross_entropy
    from apex_tpu.optimizers import FusedLAMB

    b, s, h, nh, L, V = 16, 512, 1024, 16, 24, 30528
    accum = 2                     # headline: batch 16 x 2 accum
    hd = h // nh
    f = 4 * h
    rng = np.random.RandomState(0)
    bf = jnp.bfloat16
    out = {}

    def t_chain(fn_one, x0, *consts, reps=24):
        def loss(x, *cs):
            def body(c, _):
                return fn_one(c, *cs), None
            y, _ = jax.lax.scan(body, x, None, length=reps)
            return jnp.mean(y.astype(jnp.float32))
        g = jax.jit(jax.grad(loss, argnums=tuple(range(1 + len(consts)))))
        return _time_steps(g, (x0,) + consts, warmup=1, iters=4,
                           rounds=3) / reps

    q = jnp.asarray(rng.randn(b, nh, s, hd), bf)
    k = jnp.asarray(rng.randn(b, nh, s, hd), bf)
    v = jnp.asarray(rng.randn(b, nh, s, hd), bf)
    out["attention"] = accum * L * t_chain(
        lambda q, k, v: flash_attention(q, k, v, causal=False), q, k, v)
    del q, k, v
    jax.clear_caches()

    x = jnp.asarray(rng.randn(b * s, h), bf)
    wqkv = jnp.asarray(rng.randn(h, 3 * h) * 0.02, bf)
    wproj = jnp.asarray(rng.randn(h, h) * 0.02, bf)
    out["qkv_proj_gemms"] = accum * L * t_chain(
        lambda x, a, c: ((x @ a)[:, :h] @ c), x, wqkv, wproj)
    del wqkv, wproj
    jax.clear_caches()

    w1 = jnp.asarray(rng.randn(h, f) * 0.02, bf)
    w2 = jnp.asarray(rng.randn(f, h) * 0.02, bf)
    out["ffn"] = accum * L * t_chain(
        lambda x, w1, w2: jax.nn.gelu(x @ w1, approximate=True) @ w2,
        x, w1, w2, reps=8)
    del w1, w2
    jax.clear_caches()

    ln = MixedFusedLayerNorm(h)
    lp = ln.init_params()
    xf = jnp.asarray(rng.randn(b, s, h), bf)
    out["layernorm"] = accum * 2 * L * t_chain(
        lambda x, p: ln(p, x), xf, lp, reps=48)
    del xf, lp
    jax.clear_caches()

    emb = jnp.asarray(rng.randn(V, h) * 0.02, bf)
    tgt = jnp.asarray(rng.randint(0, V, (b * s,)))
    g = jax.jit(jax.grad(lambda hd_, w: jnp.mean(
        fused_linear_cross_entropy(hd_, w, tgt)), argnums=(0, 1)))
    out["lm_head_ce"] = accum * _time_steps(g, (x, emb), warmup=1,
                                            iters=4, rounds=3)
    del x, emb, tgt, g
    jax.clear_caches()

    shapes = []
    for _ in range(L):
        shapes += [(3 * h, h), (3 * h,), (h, h), (h,), (f, h), (f,),
                   (h, f), (h,), (h,), (h,), (h,), (h,)]
    shapes += [(V, h), (512, h), (2, h), (h, h), (h,), (h,), (h,)]
    params = [jnp.asarray(rng.randn(*sh).astype(np.float32) * 0.02)
              for sh in shapes]
    grads = [jnp.asarray(rng.randn(*sh).astype(np.float32) * 1e-3)
             for sh in shapes]
    lamb = FusedLAMB(lr=1e-3, bucketed=False)
    lstate = lamb.init(params)
    reps = 4

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def lamb_steps(grads, params, state):
        def body(c, _):
            p, s_ = c
            return lamb.step(grads, p, s_), None
        (p, s_), _ = jax.lax.scan(body, (params, state), None, length=reps)
        return p, s_

    holder = {"p": params, "s": lstate}

    def run(grads):
        holder["p"], holder["s"] = lamb_steps(grads, holder["p"],
                                              holder["s"])
        return holder["p"]

    out["optimizer_lamb_per_leaf"] = _time_steps(
        run, (grads,), warmup=1, iters=2, rounds=3) / reps
    del holder, params, grads, lstate
    jax.clear_caches()

    total = sum(out.values())
    return {
        **{k: round(v, 5) for k, v in out.items()},
        "sum_s": round(total, 5),
        "top_consumer": max(out, key=out.get),
        "note": "isolated fwd+bwd per component x layer count x 2 "
                "accum microbatches at the headline batch-16 shapes; "
                "optimizer once per step (after accumulation)",
    }


# ---------------------------------------------------------------------------
# standalone optimizer leg
# ---------------------------------------------------------------------------

def bench_fused_adam_vs_optax():
    import optax

    from apex_tpu.optimizers import FusedAdam

    # this leg is a self-relative ratio — the calibration buffers from
    # the model legs are dead weight; free them before allocating ~9 GB
    # of optimizer state
    _free_calibration()

    rng = np.random.RandomState(1)
    shapes = []
    # BERT-like param census at HALF depth (12 layers): three optimizer
    # states (packed + per-leaf + optax) must coexist for the
    # same-window ratios, and the full-depth census OOMs 16 GB HBM
    # with all three alive; the ratios are depth-independent
    for _ in range(12):
        shapes += [(1024, 1024), (4096, 1024), (1024, 4096),
                   (1024,), (4096,), (1024,), (1024,)]
    shapes += [(30522, 1024), (512, 1024)]
    params = [jnp.asarray(rng.randn(*s).astype(np.float32) * 0.02)
              for s in shapes]
    grads = [jnp.asarray(rng.randn(*s).astype(np.float32) * 1e-3)
             for s in shapes]

    packed = _packed_opt(FusedAdam, lr=1e-3)
    pstate = packed.init(params)

    @jax.jit
    def packed_step(grads, params, state):
        return packed.step(grads, params, state)

    leaf = FusedAdam(lr=1e-3, bucketed=False)
    lstate = leaf.init(params)

    @jax.jit
    def leaf_step(grads, params, state):
        return leaf.step(grads, params, state)

    opt = optax.adam(1e-3)
    ostate = opt.init(params)

    @jax.jit
    def optax_step(grads, params, state):
        updates, new_state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state

    # The tunnel's absolute timing drifts between windows, so each pass
    # times all three arms back-to-back in one window; the headline is
    # the median per-pass ratio with the spread shipped.
    #
    # Caveat on the PACKED ratio's meaning: this microbenchmark hands
    # the step PRE-MATERIALIZED grads, so the bucket packing is a pure
    # extra HBM round trip here — AND a pallas_call's operands must be
    # materialized buffers, so unlike the per-leaf path the packing can
    # never fuse into the in-graph gradient producers either
    # (bench_lamb_in_step measures exactly that in-step).  The packed
    # engine's remaining wins are the ZeRO collective/state layout and
    # the on-device noop-skip; per-leaf is the single-chip speed path.
    passes = []
    for _ in range(5):
        t_packed = _time_steps(packed_step, (grads, params, pstate),
                               warmup=1, rounds=1)
        t_leaf = _time_steps(leaf_step, (grads, params, lstate),
                             warmup=1, rounds=1)
        t_optax = _time_steps(optax_step, (grads, params, ostate),
                              warmup=1, rounds=1)
        passes.append({"packed": t_packed, "leaf": t_leaf,
                       "optax": t_optax})
    passes.sort(key=lambda p: p["optax"] / p["leaf"])
    mid = passes[len(passes) // 2]

    # fp16 leg: Mosaic has no f16, so fp16 buckets take the documented
    # jnp fallback (ops/multi_tensor.py::_use_kernel) — quantify what
    # that path costs relative to the f32 Pallas path on the same
    # element count.  Same optimizer configuration on both sides.
    del ostate, lstate
    params16 = [p.astype(jnp.float16) for p in params]
    grads16 = [g.astype(jnp.float16) for g in grads]
    fused16 = _packed_opt(FusedAdam, lr=1e-3)
    fstate16 = fused16.init(params16)

    @jax.jit
    def fused16_step(grads, params, state):
        return fused16.step(grads, params, state)

    fp16_passes = []
    for _ in range(3):
        t16 = _time_steps(fused16_step, (grads16, params16, fstate16),
                          warmup=1, rounds=1)
        t32 = _time_steps(packed_step, (grads, params, pstate),
                          warmup=1, rounds=1)
        fp16_passes.append(t16 / t32)
    fp16_passes.sort()

    return {
        "n_tensors": len(shapes),
        "n_elements": int(sum(int(np.prod(s)) for s in shapes)),
        "packed_step_s": mid["packed"],
        "per_leaf_step_s": mid["leaf"],
        "optax_step_s": mid["optax"],
        "per_leaf_vs_optax_speedup": round(mid["optax"] / mid["leaf"], 3),
        "packed_vs_optax_speedup": round(mid["optax"] / mid["packed"], 3),
        "spread_leaf_vs_optax": [round(p["optax"] / p["leaf"], 3)
                                 for p in passes],
        "fp16_fallback_vs_f32_kernel": round(
            fp16_passes[len(fp16_passes) // 2], 3),
        "fp16_fallback_spread": [round(r, 3) for r in fp16_passes],
    }


def bench_dp_comm():
    """Data-parallel comms leg (PR 2): the same Adam update at dp>=2 as
    (a) replicated — psum all grads, every device runs the full per-leaf
    update (the pre-PR-2 DP path); (b) sharded-update —
    DistributedFusedAdam's reduce-scatter / 1-of-dp shard update /
    all-gather (arXiv:2004.13336); (c) sharded + int8 block-quantized
    grad transport (EQuARX, arXiv:2506.17615).  Reports step time per
    arm; the acceptance bar is sharded <= replicated at dp>=2 (on a
    single chip there is no dp to measure, so the leg degrades to a
    skip marker)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import DistributedFusedAdam
    from apex_tpu.utils.collectives import shard_map_compat

    dp = len(jax.devices())
    if dp < 2:
        return {"skipped": f"needs dp>=2, have {dp} device(s)"}
    _free_calibration()
    mesh = jax.make_mesh((dp,), ("data",))
    rng = np.random.RandomState(2)
    shapes = []
    for _ in range(4):
        shapes += [(512, 512), (2048, 512), (512, 2048), (512,), (2048,)]
    shapes += [(8192, 512)]
    params = {f"p{i}": jnp.asarray(rng.randn(*s).astype(np.float32) * 0.02)
              for i, s in enumerate(shapes)}
    # stacked per-device microbatch grads, sharded over the data axis —
    # the same input every arm consumes (its reduction is what differs)
    grads = {k: jnp.asarray(rng.randn(dp, *v.shape).astype(np.float32)
                            * 1e-3) for k, v in params.items()}
    g_specs = jax.tree_util.tree_map(lambda _: P("data"), params)

    leaf = FusedAdam(lr=1e-3, bucketed=False)
    lstate = leaf.init(params)

    @jax.jit
    def replicated_step(g, p, s):
        def local(g, p, s):
            g = jax.tree_util.tree_map(lambda x: x[0], g)
            g = jax.tree_util.tree_map(
                lambda x: jax.lax.psum(x, "data") / dp, g)
            return leaf.step(g, p, s)
        return shard_map_compat(local, mesh=mesh,
                                in_specs=(g_specs, P(), P()),
                                out_specs=(P(), P()))(g, p, s)

    arms = {}

    def rep_arm():
        return _time_steps(replicated_step, (grads, params, lstate),
                           warmup=2, iters=4, rounds=3)
    arms["replicated"] = _retry(rep_arm)

    for name, mode in (("sharded", None), ("sharded_int8", "int8")):
        opt = DistributedFusedAdam(lr=1e-3, world_size=dp,
                                   allreduce_dtype=mode)
        state = opt.make_init(mesh)(params)
        step = opt.make_step(mesh)

        def dist_arm():
            return _time_steps(step, (grads, params, state),
                               warmup=2, iters=4, rounds=3)
        arms[name] = _retry(dist_arm)
        jax.clear_caches()

    out = {"dp": dp,
           "n_elements": int(sum(int(np.prod(s)) for s in shapes)),
           "step_time_s": {k: (round(v, 6) if v else None)
                           for k, v in arms.items()}}
    if arms["replicated"] and arms["sharded"]:
        out["sharded_vs_replicated_speedup"] = round(
            arms["replicated"] / arms["sharded"], 3)
    if arms["replicated"] and arms["sharded_int8"]:
        out["int8_vs_replicated_speedup"] = round(
            arms["replicated"] / arms["sharded_int8"], 3)
    return out


def bench_tp_overlap():
    """Tensor-parallel latency-hiding leg (ISSUE 3): the same GPT
    fwd+bwd step at tp=2/4/8 as (a) replicated — the all-gather/psum TP
    edges with sequence-replicated activations (the pre-SP path); (b)
    sequence-parallel — gather(tiled)/psum_scatter edges, LN/residual on
    ``(b, s/t, h)``; (c) sequence-parallel + chunked overlap — the TP-edge
    collective+GEMM pairs fused into ``ppermute`` rings
    (``overlap_chunks=4``).  Reports step time per arm and
    ``tp_overlap_speedup`` (replicated / best latency-hiding arm at the
    widest tp).  Degrades to a skip marker on a single chip, like
    :func:`bench_dp_comm`."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import GPTConfig, GPTModel, pack_for_shard_map
    from apex_tpu.utils.collectives import shard_map_compat

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"skipped": f"needs tp>=2, have {n_dev} device(s)"}
    _free_calibration()
    rng = np.random.RandomState(3)
    batch, seq = 2, 256

    def cfg(**kw):
        return GPTConfig(vocab_size=1024, hidden_size=256, num_layers=2,
                         num_attention_heads=8, max_seq_len=seq,
                         rotary=True, **kw)

    params = GPTModel(cfg()).init_params(jax.random.PRNGKey(0))
    tokens = jnp.asarray(rng.randint(0, 1024, (batch, seq)))
    targets = jnp.asarray(rng.randint(0, 1024, (batch, seq)))

    def arm_time(model):
        mesh = jax.make_mesh((model.cfg.tensor_parallel_size,), ("model",))
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            model, params)

        def step(sp, tokens, targets):
            loss, g = jax.value_and_grad(model.loss)(local_fn(sp), tokens,
                                                     targets)
            return loss, repack_fn(g)

        run = jax.jit(shard_map_compat(
            step, mesh=mesh, in_specs=(in_specs, P(), P()),
            out_specs=(P(), in_specs)))

        def timed():
            return _time_steps(run, (packed, tokens, targets),
                               warmup=2, iters=4, rounds=3)
        t = _retry(timed)
        jax.clear_caches()
        return t

    out = {"batch": batch, "seq_len": seq, "per_tp": {}}
    speedup = None
    for tp in (2, 4, 8):
        if tp > n_dev:
            break
        arms = {
            "replicated": arm_time(GPTModel(cfg(
                tensor_parallel_size=tp, axis_name="model"))),
            "sequence_parallel": arm_time(GPTModel(cfg(
                tensor_parallel_size=tp, axis_name="model",
                sequence_parallel=True))),
            "sp_chunked": arm_time(GPTModel(cfg(
                tensor_parallel_size=tp, axis_name="model",
                sequence_parallel=True, overlap_chunks=4))),
        }
        row = {"step_time_s": {k: (round(v, 6) if v else None)
                               for k, v in arms.items()}}
        best = min((v for k, v in arms.items()
                    if k != "replicated" and v), default=None)
        if arms["replicated"] and best:
            speedup = round(arms["replicated"] / best, 3)
            row["tp_overlap_speedup"] = speedup
        out["per_tp"][f"tp{tp}"] = row
    # headline: the widest mesh measured (speedup carries tp by tp above)
    out["tp_overlap_speedup"] = speedup
    return out


def bench_pp_schedules():
    """Pipeline-parallel leg (ISSUE 6): the same GPT fwd+bwd step as
    (a) single-stage — one device, plain ``value_and_grad`` over the
    full microbatch set; (b) 1F1B ``pipeline_step`` at pp=2 and pp=4;
    (c) interleaved virtual stages (``n_virtual=2``) at the same
    widths.  Each pipelined arm reports its analytic bubble fraction
    next to the measured step time: 1F1B idles (S-1)/(M+S-1) of the
    schedule, interleaving cuts that to (S-1)/(Mv+(v+1)S-2) ticks'
    worth at the cost of v x more ppermute hops — the measurement
    shows whether the wire cost eats the bubble win at each width.
    ``vs_single_stage`` is wall-clock speedup over the one-device arm
    (upper bound S, bubble + p2p overhead eat the rest)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import GPTConfig, GPTModel, pack_for_shard_map
    from apex_tpu.models.gpt import pipeline_step
    from apex_tpu.transformer.pipeline_parallel import bubble_fraction
    from apex_tpu.utils.collectives import shard_map_compat

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"skipped": f"needs pp>=2, have {n_dev} device(s)"}
    _free_calibration()
    rng = np.random.RandomState(5)
    # 8 layers: divisible into S*v chunks for every (S, v) below;
    # M=8 microbatches satisfies the interleaved M % S == 0 constraint
    M, mb, seq = 8, 1, 256
    cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=8,
                    num_attention_heads=8, max_seq_len=seq, rotary=True)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    tokens = jnp.asarray(rng.randint(0, 1024, (M * mb, seq)))
    targets = jnp.asarray(rng.randint(0, 1024, (M * mb, seq)))

    def single_stage_arm():
        run = jax.jit(jax.value_and_grad(model.loss))

        def timed():
            return _time_steps(run, (params, tokens, targets),
                               warmup=2, iters=4, rounds=3)
        t = _retry(timed)
        jax.clear_caches()
        return t

    def pp_arm(S, v):
        mesh = jax.make_mesh((S,), ("pipe",), devices=jax.devices()[:S])
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            model, params, n_stages=S, tensor_axis=None, n_virtual=v)

        def step(sp, tk, tg):
            loss, g = pipeline_step(model, local_fn(sp),
                                    tk.reshape(M, mb, seq),
                                    tg.reshape(M, mb, seq),
                                    pipe_axis="pipe", n_virtual=v)
            return loss, repack_fn(g)

        run = jax.jit(shard_map_compat(
            step, mesh=mesh, in_specs=(in_specs, P(), P()),
            out_specs=(P(), in_specs)))

        def timed():
            return _time_steps(run, (packed, tokens, targets),
                               warmup=2, iters=4, rounds=3)
        t = _retry(timed)
        jax.clear_caches()
        return t

    out = {"microbatches": M, "micro_batch_size": mb, "seq_len": seq,
           "n_layers": cfg.num_layers, "per_pp": {}}
    t_single = single_stage_arm()
    out["single_stage_step_s"] = round(t_single, 6) if t_single else None
    for S in (2, 4):
        if S > n_dev:
            break
        row = {}
        for name, v in (("1f1b", 1), ("interleaved", 2)):
            t = pp_arm(S, v)
            cell = {"step_time_s": round(t, 6) if t else None,
                    "bubble_fraction": round(bubble_fraction(M, S, v), 4)}
            if t and t_single:
                cell["vs_single_stage"] = round(t_single / t, 3)
            row[name] = cell
        a, b = (row["1f1b"]["step_time_s"],
                row["interleaved"]["step_time_s"])
        if a and b:
            row["interleaved_vs_1f1b_speedup"] = round(a / b, 3)
        out["per_pp"][f"pp{S}"] = row
    return out


def bench_resilience():
    """Resilience leg (ISSUE 4): what fault tolerance costs.

    (a) Checkpoint save / restore wall seconds for a full train state
    (params + both FusedAdam slots + step counter) through
    CheckpointManager's atomic commit protocol (payload + sha256
    manifest + latest-symlink flip), plus the async enqueue latency —
    the time the train loop actually stalls when double-buffered
    writes are used.  (b) Guarded vs raw train-step overhead: the SAME
    loss + FusedAdam update run bare vs through GuardedTrainStep
    (in-graph grad-norm/finiteness checks + the per-step host readback
    of the 3-element flags vector).  Acceptance target: overhead < 2%.
    """
    import shutil
    import tempfile

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import CheckpointManager, GuardedTrainStep

    _free_calibration()
    rng = np.random.RandomState(4)
    shapes = []
    for _ in range(4):
        shapes += [(512, 512), (2048, 512), (512, 2048), (512,), (2048,)]
    shapes += [(8192, 512)]
    params = {f"p{i}": jnp.asarray(rng.randn(*s).astype(np.float32) * 0.02)
              for i, s in enumerate(shapes)}
    n_elements = int(sum(int(np.prod(s)) for s in shapes))
    adam = FusedAdam(lr=1e-3, bucketed=False)
    opt_state = adam.init(params)

    # -- checkpoint save / restore -------------------------------------
    state = {"params": params, "opt": opt_state,
             "step": jnp.zeros((), jnp.int32)}
    ckdir = tempfile.mkdtemp(prefix="apex_tpu_bench_ck_")
    try:
        mgr = CheckpointManager(ckdir, keep=2)
        saves, restores, enqueues = [], [], []
        for i in range(3):
            t0 = time.perf_counter()
            mgr.save(i, state)
            saves.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            mgr.restore(state)
            restores.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            mgr.save_async(100 + i, state)   # train-loop stall only
            enqueues.append(time.perf_counter() - t0)
        mgr.wait()
        saves.sort(); restores.sort(); enqueues.sort()
        ck = {"state_bytes": 3 * 4 * n_elements,
              "save_s": round(saves[1], 4),
              "restore_s": round(restores[1], 4),
              "async_enqueue_s": round(enqueues[1], 4)}
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)

    # -- guard overhead ------------------------------------------------
    # measured on a real (small) GPT fwd+bwd+Adam step so the guard's
    # extra work — the in-graph grad-norm pass, the injection-flag
    # folding, and the per-step host readback of the 3-float flags
    # vector — is weighed against realistic step compute, the way a
    # production train loop would pay it
    from apex_tpu.models.gpt import GPTConfig, GPTModel

    cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=2,
                    num_attention_heads=8, max_seq_len=256)
    model = GPTModel(cfg)
    gparams = model.init_params(jax.random.PRNGKey(0))
    gadam = FusedAdam(lr=1e-4, bucketed=False)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 256)))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 256)))

    @jax.jit
    def raw_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens,
                                                     targets)
        new_p, new_o = gadam.step(grads, params, opt_state)
        return loss, new_p, new_o

    hr = {"p": gparams, "o": gadam.init(gparams)}

    def run_raw(tokens, targets):
        loss, hr["p"], hr["o"] = raw_step(hr["p"], hr["o"], tokens,
                                          targets)
        return loss

    guard = GuardedTrainStep(model.loss, gadam)
    hg = {"p": gparams, "o": gadam.init(gparams),
          "g": guard.init_state()}

    def run_guard(tokens, targets):
        r = guard(hg["p"], hg["o"], hg["g"], tokens, targets)
        hg["p"], hg["o"], hg["g"] = r.params, r.opt_state, r.guard_state
        return r.loss

    t_raw = _time_steps(run_raw, (tokens, targets), warmup=2, iters=4,
                        rounds=3)
    t_guard = _time_steps(run_guard, (tokens, targets), warmup=2,
                          iters=4, rounds=3)
    overhead = t_guard / t_raw - 1.0
    return {"n_elements": n_elements, "checkpoint": ck,
            "raw_step_s": round(t_raw, 6),
            "guarded_step_s": round(t_guard, 6),
            "guard_overhead_frac": round(overhead, 4),
            "guard_overhead_target": 0.02,
            "guard_overhead_ok": bool(overhead < 0.02)}


def bench_elastic():
    """Elastic leg (ISSUE 9): what a topology re-plan costs.

    An :class:`ElasticTrainer` runs a guarded FusedAdam loop and is
    asked — through the :class:`HostSignals` mailbox, the SIGTERM
    route — to shrink dp to half and later grow back.  Each re-plan
    decomposes into the trainer's own phase stats (``checkpoint_s``:
    drain + boundary save, ``reshard_s``: rebuild + re-partition +
    post-reshard save) plus a measured ``recompile_s``: the first step
    under the new topology minus the steady-state median step (XLA
    retraces because the mesh changed).  ``total_recovery_s`` is the
    sum — the wall time a preempted pod spends not training.  Runs on
    any device count (dp=1 -> dp=1 still exercises the full
    drain/checkpoint/reshard/recompile cycle)."""
    import shutil
    import tempfile

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import (ElasticComponents, ElasticPlan,
                                     ElasticTrainer, GuardedTrainStep,
                                     HostSignals, TopologySpec)

    _free_calibration()
    n = len(jax.devices())
    dp = 4 if n >= 4 else (2 if n >= 2 else 1)
    base = TopologySpec(dp=dp)
    shrink = TopologySpec(dp=max(1, dp // 2))

    def loss_fn(p, x, y):
        return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))

    def factory(plan, ckpt, inj):
        opt = FusedAdam(lr=1e-3, bucketed=False)
        guard = GuardedTrainStep(loss_fn, opt, warmup_steps=1,
                                 checkpoint=ckpt, fault_injector=inj)
        r = np.random.RandomState(7)
        params = plan.put(
            {"w": jnp.asarray((r.randn(512, 256) * 0.02).astype(np.float32)),
             "b": jnp.zeros((256,), jnp.float32)})
        return ElasticComponents(guard, params, opt.init(params),
                                 guard.init_state())

    n_steps = 10
    signals = HostSignals()
    stamps = {}

    def batch_fn(step, plan):
        # one timestamp per executed step: gap s -> s+1 is the cost of
        # executing step s (+ the re-plan when one precedes step s+1)
        stamps.setdefault(step, time.perf_counter())
        if step == 3:
            signals.request_replan(shrink)
        elif step == 6:
            signals.request_replan(base)
        r = np.random.RandomState(9_000 + step)
        return (jnp.asarray(r.randn(64, 512).astype(np.float32)),
                jnp.asarray(r.randn(64, 256).astype(np.float32)))

    root = tempfile.mkdtemp(prefix="apex_tpu_bench_elastic_")
    try:
        trainer = ElasticTrainer(
            factory, ElasticPlan.build(base), directory=root,
            signals=signals)
        out = trainer.train(batch_fn, n_steps)
        stamps[n_steps] = time.perf_counter()
        assert out["replans"] == 2, out
        gap = {s: stamps[s + 1] - stamps[s] for s in range(n_steps)}
        # signals requested at steps 3/6 land at the NEXT poll, so the
        # re-plans precede steps 4 and 7: gaps 3 and 6 absorb the
        # re-plan, gaps 4 and 7 absorb the recompile
        steady = float(np.median([gap[s] for s in (1, 2, 5, 8)]))
        recompile = max(0.0,
                        float(np.median([gap[4], gap[7]])) - steady)
        ck = trainer.stats["last_checkpoint_s"]
        rs = trainer.stats["last_reshard_s"]
        return {"dp": dp, "shrink_dp": shrink.dp,
                "replans": out["replans"],
                "steady_step_s": round(steady, 5),
                "checkpoint_s": round(ck, 5),
                "reshard_s": round(rs, 5),
                "recompile_s": round(recompile, 5),
                "total_recovery_s": round(ck + rs + recompile, 5)}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_capacity():
    """Capacity-shifting leg (ROADMAP item 4): what moving chips
    between training and serving costs.

    A :class:`CapacityController` over a FusedAdam elastic trainer and
    a two-replica paged fleet runs one full lease cycle — shift
    **to_serving** (boundary-checkpoint drain + shrink re-shard +
    replica start) then **to_training** (replica migration drain +
    remove + grow re-shard) — and reports each shift's phase
    decomposition from the controller's own stats: ``drain_s``,
    ``reshard_s``, ``commit_s``, ``total_s`` (wall; the controller is
    given a wall clock while the fleet stays on its virtual one), plus
    the fleet ticks the serving drain took.  These are the latency
    numbers an operator trades against the SLO burn a shift relieves."""
    import shutil
    import tempfile

    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.observability.slo import SLOMonitor, SLOTarget
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import (CapacityController, ElasticComponents,
                                     ElasticPlan, ElasticTrainer,
                                     GuardedTrainStep, TopologySpec)
    from apex_tpu.serving import (FleetRouter, PagedInferenceEngine,
                                  TickScheduler, VirtualClock)
    from apex_tpu.utils.profiling import ServingMetrics

    _free_calibration()
    n = len(jax.devices())
    if n < 2:
        return {"skipped": "needs >= 2 devices"}
    dp = 4 if n >= 4 else 2

    def loss_fn(p, x, y):
        return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))

    def factory(plan, ckpt, inj):
        opt = FusedAdam(lr=1e-3, bucketed=False)
        guard = GuardedTrainStep(loss_fn, opt, warmup_steps=1,
                                 checkpoint=ckpt, fault_injector=inj)
        r = np.random.RandomState(7)
        params = plan.put(
            {"w": jnp.asarray((r.randn(512, 256) * 0.02).astype(np.float32)),
             "b": jnp.zeros((256,), jnp.float32)})
        return ElasticComponents(guard, params, opt.init(params),
                                 guard.init_state())

    def batch_fn(step, plan):
        r = np.random.RandomState(9_000 + step)
        return (jnp.asarray(r.randn(64, 512).astype(np.float32)),
                jnp.asarray(r.randn(64, 256).astype(np.float32)))

    clock = VirtualClock()
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_attention_heads=2, max_seq_len=64)
    model = GPTModel(cfg)
    mparams = model.init_params(jax.random.PRNGKey(0))

    def make_replica():
        slo = SLOMonitor([SLOTarget("ttft", 0.1, objective=0.9)],
                         clock=clock)
        return PagedInferenceEngine(
            model, mparams, max_slots=4, block_size=8,
            scheduler=TickScheduler(token_budget=64),
            metrics=ServingMetrics(clock, slo=slo), max_queue=32,
            clock=clock)

    fleet = FleetRouter([make_replica(), make_replica()], clock=clock)
    root = tempfile.mkdtemp(prefix="apex_tpu_bench_capacity_")
    try:
        trainer = ElasticTrainer(
            factory, ElasticPlan.build(TopologySpec(dp=dp)),
            directory=root, save_every=1)
        ctl = CapacityController(
            trainer, fleet, make_replica, min_train_dp=max(1, dp // 2),
            cooldown_s=0.0, clock=time.perf_counter)
        for _ in range(3):            # compile + steady state
            trainer.step_once(batch_fn)

        ctl.request_shift("to_serving")
        fleet.step()
        ctl.tick()
        clock.advance(0.01)
        assert ctl.stats["shifts"] == 1, ctl.shift_log
        to_serving = dict(ctl.stats["last_shift"])

        trainer.step_once(batch_fn)   # absorb the shrunk-plan recompile

        ctl.request_shift("to_training")
        ticks = 0
        while ctl.outstanding_leases or ctl.shifting:
            fleet.step()
            ctl.tick()
            clock.advance(0.01)
            ticks += 1
            assert ticks < 200, "capacity drain did not converge"
        assert ctl.stats["shifts"] == 2, ctl.shift_log
        to_training = dict(ctl.stats["last_shift"])

        rnd = lambda d: {k: (round(v, 5) if isinstance(v, float) else v)
                         for k, v in d.items()}
        return {"dp": dp, "shrink_dp": max(1, dp // 2),
                "replicas_leased": (dp - max(1, dp // 2)),
                "to_serving": rnd(to_serving),
                "to_training": rnd(to_training),
                "serving_drain_ticks": ticks}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_autopilot():
    """Self-driving-parallelism leg (ROADMAP item 3): what the closed
    drift -> refit -> re-rank -> gated-adoption loop costs.

    A :class:`ParallelismAutopilot` over a FusedAdam elastic trainer
    runs one full cycle against an injected interconnect drift: links
    go 16x slower (``cost_drift``), the refit window confirms it and
    the re-ranked plan commits through the measured baseline -> drain
    -> gate protocol; the links then recover with a
    ``plan_regression`` poisoning the re-adoption's gate, forcing the
    measured rollback.  Reported per phase, from the autopilot's own
    stats: ``refit_s`` (incremental cost-model refit), ``rank_s``
    (plan-space re-rank), ``drain_s`` + ``reshard_s`` (the adoption's
    boundary checkpoint and re-shard — the only training-visible
    cost), and ``rollback_s`` (replan back to the stamped old plan).
    Step times are driver-synthesized from the drifted alpha-beta
    curve (the controller is under test, not the toy model); the
    checkpoint/re-shard/rollback numbers are real wall time over the
    512x256 elastic trainer."""
    import shutil
    import tempfile

    from apex_tpu.observability import MetricsRegistry
    from apex_tpu.observability.costmodel import (
        CostFit, fit_cost_model, simulate_link_measurements)
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import (ElasticComponents, ElasticPlan,
                                     ElasticTrainer, Fault, FaultInjector,
                                     GuardedTrainStep,
                                     ParallelismAutopilot, TopologySpec)

    _free_calibration()
    n = len(jax.devices())
    if n < 2:
        return {"skipped": "needs >= 2 devices"}
    dp = 4 if n >= 4 else 2

    def loss_fn(p, x, y):
        return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))

    def factory(plan, ckpt, inj):
        opt = FusedAdam(lr=1e-3, bucketed=False)
        guard = GuardedTrainStep(loss_fn, opt, warmup_steps=1,
                                 checkpoint=ckpt, fault_injector=inj)
        r = np.random.RandomState(7)
        params = plan.put(
            {"w": jnp.asarray((r.randn(512, 256) * 0.02).astype(np.float32)),
             "b": jnp.zeros((256,), jnp.float32)})
        return ElasticComponents(guard, params, opt.init(params),
                                 guard.init_state())

    def batch_fn(step, plan):
        r = np.random.RandomState(9_000 + step)
        return (jnp.asarray(r.randn(64, 512).astype(np.float32)),
                jnp.asarray(r.randn(64, 256).astype(np.float32)))

    alpha0, beta0 = 2e-3, 1e-9
    grad_bytes = 512 * 256 * 4 + 256 * 4
    serial_s = 0.12

    def step_dt(step, cur_dp):
        scale = 1.0
        if step >= 2:
            scale *= 16.0
        if step >= 8:
            scale /= 16.0
        fit = CostFit(alpha0 * scale, beta0 * scale)
        comm = fit.predict("psum", grad_bytes, cur_dp) if cur_dp > 1 \
            else 0.0
        return serial_s / cur_dp + comm

    profile = fit_cost_model(
        simulate_link_measurements(alpha0, beta0, link_class="dcn",
                                   ops=("psum",)),
        meta={"source": "bench_autopilot"})
    inj = FaultInjector([Fault(2, "cost_drift", magnitude=16.0),
                         Fault(8, "cost_drift", magnitude=1.0 / 16.0),
                         Fault(8, "plan_regression", magnitude=4.0)])
    root = tempfile.mkdtemp(prefix="apex_tpu_bench_autopilot_")
    try:
        reg = MetricsRegistry()
        trainer = ElasticTrainer(
            factory, ElasticPlan.build(TopologySpec(dp=dp)),
            directory=root, save_every=1, fault_injector=inj)
        ap = ParallelismAutopilot(
            trainer, profile, min_dp=max(1, dp // 2),
            link_class="dcn", confirm_windows=2, min_measurements=8,
            cooldown_s=0.0, gate_steps=2, gate_tolerance=1.2,
            grad_bytes=grad_bytes, injector=inj, registry=reg)
        commit = None
        for step in range(16):
            trainer.step_once(batch_fn)
            ap.record_step(step_dt(step, trainer.plan.spec.dp))
            ap.tick()
            ap.tick()
            if commit is None and ap.stats["adoptions"] == 1:
                commit = dict(ap.stats["last_adoption"])
        assert ap.stats["adoptions"] == 1, ap.adoption_log
        assert ap.stats["rollbacks"] == 1, ap.adoption_log
        assert ap.audit() == [], ap.audit()
        rollback = dict(ap.stats["last_adoption"])

        rnd = lambda d: {k: (round(v, 5) if isinstance(v, float) else v)
                         for k, v in d.items()}
        return {"dp": dp, "shrink_dp": max(1, dp // 2),
                "grad_bytes": grad_bytes,
                "refit_windows": ap.stats["refits"],
                "refit_s": round(ap.stats["last_refit_s"], 5),
                "drift_confirmations": ap.stats["drift_confirmed"],
                "commit": rnd(commit),
                "rollback": rnd(rollback)}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_observability():
    """Observability leg (ISSUE 5): what monitoring costs.

    The SAME GuardedTrainStep GPT step run bare vs wrapped in
    ``TrainingMonitor`` (per-step wall timing, registry mutations for
    the step-time/tokens-s/grad-norm/loss/loss-scale series, one JSONL
    ``train_step`` record per step).  The monitor reads everything from
    the telemetry vector the guard's host readback already materializes
    — no extra device→host syncs — so the acceptance target is < 2%
    overhead.  Also round-trips the emitted stream through
    ``replay_jsonl`` so a broken exporter fails the leg, not a later
    consumer."""
    import io

    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.observability import (MetricsRegistry, TrainingMonitor,
                                        replay_jsonl)
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import GuardedTrainStep

    _free_calibration()
    rng = np.random.RandomState(5)
    cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=2,
                    num_attention_heads=8, max_seq_len=256)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    adam = FusedAdam(lr=1e-4, bucketed=False)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 256)))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 256)))
    guard = GuardedTrainStep(model.loss, adam)

    hb = {"p": params, "o": adam.init(params), "g": guard.init_state()}

    def run_bare(tokens, targets):
        r = guard(hb["p"], hb["o"], hb["g"], tokens, targets)
        hb["p"], hb["o"], hb["g"] = r.params, r.opt_state, r.guard_state
        return r.loss

    buf = io.StringIO()
    reg = MetricsRegistry()
    reg.attach_stream(buf)
    mon = TrainingMonitor(reg, tokens_per_step=4 * 256)
    hm = {"p": params, "o": adam.init(params), "g": guard.init_state()}

    def step_mon(tokens, targets):
        r = guard(hm["p"], hm["o"], hm["g"], tokens, targets)
        hm["p"], hm["o"], hm["g"] = r.params, r.opt_state, r.guard_state
        return r

    monitored = mon.wrap(step_mon)

    def run_mon(tokens, targets):
        return monitored(tokens, targets).loss

    # paired windows: absolute timing drifts between windows (tunnel /
    # busy host), so each pass times bare and monitored back-to-back
    # and the headline overhead is the median per-pass ratio
    passes = []
    for _ in range(5):
        t_b = _time_steps(run_bare, (tokens, targets), warmup=1,
                          iters=8, rounds=1)
        t_m = _time_steps(run_mon, (tokens, targets), warmup=1,
                          iters=8, rounds=1)
        passes.append((t_b, t_m))
    passes.sort(key=lambda p: p[1] / p[0])
    t_bare, t_mon = passes[len(passes) // 2]
    overhead = t_mon / t_bare - 1.0

    # the stream the monitored arm produced must replay and carry the
    # per-step keys an alerting pipeline needs
    replayed, records = replay_jsonl(buf.getvalue().splitlines())
    steps = [r for r in records if r.get("event") == "train_step"]
    stream_ok = (bool(steps)
                 and all({"step", "step_time_s", "tokens_per_s",
                          "grad_norm"} <= set(r) for r in steps)
                 and replayed.get("train_steps_total").value()
                 == mon.steps)
    return {"bare_step_s": round(t_bare, 6),
            "monitored_step_s": round(t_mon, 6),
            "monitor_overhead_frac": round(overhead, 4),
            "monitor_overhead_target": 0.02,
            "monitor_overhead_ok": bool(overhead < 0.02),
            "stream_records": len(records),
            "stream_ok": bool(stream_ok)}


def bench_serving_observability():
    """Serving-observability leg (ISSUE 7): what per-request tracing +
    SLO monitoring cost on the decode loop.

    The SAME continuous-batching engine workload (submit a batch of
    requests, drive ``step()`` to completion) run with default metrics
    vs fully instrumented — a ``Tracer`` attached (per-request async
    spans materialized at completion), an ``SLOMonitor`` classifying
    TTFT/token-latency/queue-wait, and the queue-wait/decode-ticks
    series live.  The hot-path additions are dict writes and int
    increments; span events materialize once per request, so the
    acceptance target is < 2% (paired windows, median per-pass ratio,
    same protocol as the training-observability leg)."""
    from apex_tpu.inference import InferenceEngine, Request
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.observability import (MetricsRegistry, SLOMonitor,
                                        SLOTarget, Tracer)
    from apex_tpu.utils.profiling import ServingMetrics

    _free_calibration()
    cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=2,
                    num_attention_heads=8, max_seq_len=128)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(1, cfg.vocab_size, 12)) for _ in range(8)]

    eng_bare = InferenceEngine(model, params, max_slots=4)
    tracer = Tracer(clock=time.monotonic)     # engine's clock domain
    slo = SLOMonitor([SLOTarget("ttft", 0.5, objective=0.95),
                      SLOTarget("token_latency", 0.1, objective=0.99)],
                     clock=time.monotonic)
    metrics = ServingMetrics(time.monotonic,
                             registry=MetricsRegistry(), slo=slo)
    eng_traced = InferenceEngine(model, params, max_slots=4,
                                 metrics=metrics, tracer=tracer)

    ids = {"n": 0}

    def run(eng):
        for p in prompts:
            ids["n"] += 1
            eng.submit(Request(request_id=ids["n"], prompt=p,
                               max_new_tokens=16))
        while eng.step():
            pass

    run(eng_bare)                             # compile outside timing
    run(eng_traced)
    passes = []
    for _ in range(5):
        t0 = time.perf_counter()
        run(eng_bare)
        t_b = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(eng_traced)
        t_t = time.perf_counter() - t0
        passes.append((t_b, t_t))
        tracer.clear()                        # bound trace growth
    passes.sort(key=lambda p: p[1] / p[0])
    t_bare, t_traced = passes[len(passes) // 2]
    overhead = t_traced / t_bare - 1.0

    # the instrumented arm must actually have produced its artifacts
    n_done = ids["n"] - len(prompts)          # warmup pass excluded
    trace_ok = (eng_traced.trace.pending == 0
                and len(metrics.decode_ticks) > 0
                and metrics._h_queue_wait.count() == ids["n"] // 2
                and slo.snapshot()["percentiles"]["ttft"]["n"] > 0)
    return {"bare_window_s": round(t_bare, 6),
            "traced_window_s": round(t_traced, 6),
            "trace_overhead_frac": round(overhead, 4),
            "trace_overhead_target": 0.02,
            "trace_overhead_ok": bool(overhead < 0.02),
            "requests_per_window": len(prompts),
            "trace_ok": bool(trace_ok)}


def bench_serving_paged():
    """Paged-serving leg (ISSUE 10): the paged engine against the
    contiguous engine on the same shared-prefix workload.

    Three timed arms over an identical request set (8 requests, half
    sharing one 32-token system prompt, 24 new tokens each): the
    contiguous engine, the paged engine (prefix sharing on), and the
    paged engine with chunked prefill.  Reported: decode throughput and
    token agreement vs contiguous per arm, the paged pool's block
    savings and prefix hit rate, and — untimed — the speculative accept
    rate with a self-draft.  The PAGED arm's parity is asserted exact
    (it is the same attention reference over a gathered pool — bitwise
    by construction); chunked prefill and the speculative verify chunk
    are a different XLA compute schedule, so their agreement is
    MEASURED, not assumed — on a random-init model with near-flat
    logits even last-ulp rounding flips argmax, which trained-model
    margins absorb (the tier-1 tests pin exact agreement at their
    configs)."""
    from apex_tpu.inference import InferenceEngine, Request
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.serving import (PagedInferenceEngine, SpeculativeConfig,
                                  TickScheduler)

    _free_calibration()
    cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=2,
                    num_attention_heads=8, max_seq_len=128)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(11)
    sysp = list(rng.randint(1, cfg.vocab_size, 32))
    prompts = [(sysp if i % 2 == 0 else []) +
               list(rng.randint(1, cfg.vocab_size, 12))
               for i in range(8)]

    def workload():
        return [Request(request_id=i, prompt=p, max_new_tokens=24)
                for i, p in enumerate(prompts)]

    def drive(eng):
        for r in workload():
            eng.submit(r)
        out = eng.run()
        return ({r.request_id: r.tokens for r in out},
                sum(len(r.tokens) for r in out))

    arms = {}
    tokens_ref = None
    mk = {
        "contiguous": lambda: InferenceEngine(model, params, max_slots=4),
        "paged": lambda: PagedInferenceEngine(model, params, max_slots=4,
                                              block_size=16),
        "paged_chunked": lambda: PagedInferenceEngine(
            model, params, max_slots=4, block_size=16,
            chunked_prefill=True,
            scheduler=TickScheduler(token_budget=64, min_chunk=16,
                                    max_chunk=32)),
    }
    pool_stats = {}

    def agreement(toks):
        return sum(toks[i] == tokens_ref[i] for i in tokens_ref) \
            / len(tokens_ref)

    for name, make in mk.items():
        drive(make())                          # compile outside timing

        def timed(make=make, name=name):
            eng = make()
            t0 = time.perf_counter()
            toks, n = drive(eng)
            dt = time.perf_counter() - t0
            if hasattr(eng, "pool"):
                pool_stats[name] = eng.pool.stats()
            return toks, n, dt
        got = _retry(timed)
        if got is None:
            arms[name] = None
            continue
        toks, n, dt = got
        if tokens_ref is None:
            tokens_ref = toks
        agree = agreement(toks)
        if name == "paged":                    # bitwise by construction
            assert agree == 1.0, "paged arm diverged from contiguous"
        arms[name] = {"tokens": n, "window_s": round(dt, 6),
                      "tokens_per_s": round(n / dt, 2),
                      "token_agreement": round(agree, 4)}

    # speculative arm (untimed): accept rate + stream agreement
    spec = PagedInferenceEngine(
        model, params, max_slots=4, block_size=16,
        speculative=SpeculativeConfig(model, params, num_tokens=3))
    toks, _ = drive(spec)
    ps = pool_stats.get("paged", {})
    lookup = ps.get("prefix_lookup_tokens", 0)
    return {
        "arms": arms,
        "prefix_hit_rate": round(ps.get("prefix_hit_tokens", 0) / lookup,
                                 4) if lookup else 0.0,
        "paged_pool": ps,
        "spec_accept_rate": round(spec.spec_accept_rate, 4),
        "spec_token_agreement": round(agreement(toks), 4),
        "paged_parity_ok": True,
    }


def bench_serving_chaos():
    """Serving-chaos leg (ISSUE 12): recovery time under replica loss.

    Two chaos scenarios from ``tools/loadgen.py`` on a 3-replica CPU
    fleet over a virtual clock (deterministic, sleep-free):

    * ``replica_kill`` — a replica crashes mid-run; the metric is the
      detection -> migration -> first-resumed-token chain from the
      fleet's recovery report, in ticks and virtual seconds.
    * ``bursty`` — synchronized arrival bursts stress admission,
      retry/backoff, and the degradation ladder.

    Both scenarios are HARD-GATED on the exactly-once ledger (zero lost,
    zero client-visible duplicates) and on SLO attainment: losing a
    replica may cost tail latency, but never correctness."""
    import argparse
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    def ns(**kw):
        base = dict(
            scenario="replica_kill", requests=16, rate=1e9, replicas=3,
            max_slots=2, max_queue=64, max_queue_depth=4,
            burn_threshold=14.4, burn_window_s=60.0, ttft_slo_s=0.5,
            block_size=4, chunked=False, token_budget=32,
            client_retries=3, tick_s=0.02, e2e_slo_s=3.0, max_ticks=2000,
            retry_budget=4, hedge_after_s=None, ladder_step_down_s=0.5,
            kill_tick=4, kill_replica=1, kill_duration=10 ** 6,
            slow_tick=4, slow_s=0.1, slow_duration=40, burst_n=6,
            burst_gap_s=0.3, period_s=2.0, seed=0, min_prompt=4,
            pareto_shape=2.5, max_new=6, shared_prefix_prob=0.5,
            shared_prefix_len=8, num_prefixes=2, vocab=64, hidden=32,
            layers=2, heads=2, max_seq=48)
        base.update(kw)
        return argparse.Namespace(**base)

    out = {}
    for scenario in ("replica_kill", "bursty"):
        rep = loadgen.run_scenario(ns(scenario=scenario))
        # correctness gates: exactly-once, nothing stranded
        assert rep["lost"] == [], (scenario, rep["lost"])
        assert rep["duplicated"] == 0, scenario
        assert rep["fleet_pending"] == 0, scenario
        assert rep["slo_attainment"] >= 0.9, (scenario,
                                              rep["slo_attainment"])
        leg = {"responses": rep["responses"],
               "served": rep["e2e_served"],
               "slo_attainment": rep["slo_attainment"],
               "e2e_p50_s": rep["e2e_p50_s"],
               "e2e_p99_s": rep["e2e_p99_s"],
               "retries": rep["retries"],
               "migrations": rep["migrations"],
               "degraded_max_level": rep["degraded_max_level"],
               "ticks": rep["ticks"]}
        if scenario == "replica_kill":
            rec = rep["recovery"]
            assert rec["first_dead"] is not None, "kill never detected"
            assert rec["first_resumed_token"] is not None, \
                "migrated work never resumed"
            dead, resumed = rec["first_dead"], rec["first_resumed_token"]
            kill_t = ns().kill_tick * ns().tick_s
            leg["recovery"] = {
                "detect_ticks": dead["tick"] - ns().kill_tick,
                "detect_s": round(dead["t"] - kill_t, 4),
                "resume_ticks": resumed["tick"] - ns().kill_tick,
                "kill_to_first_resumed_token_s": round(
                    resumed["t"] - kill_t, 4)}
            assert leg["recovery"]["kill_to_first_resumed_token_s"] \
                >= 0.0
        out[scenario] = leg
    out["exactly_once_ok"] = True
    return out


def bench_serving_disagg():
    """Disaggregated-serving leg (ISSUE 16): the two-pool fleet and the
    quantized KV cache against the single-engine arms.

    Five arms over an identical request set (8 requests, half sharing
    one 32-token system prompt, 24 new tokens each):

    * ``contiguous`` — the slot-ring engine (KV bytes/user is the full
      preallocated ``max_seq`` stripe);
    * ``paged`` — the paged engine with chunked prefill (the mode
      every disagg engine runs, and the arm agreement is measured
      against);
    * ``disagg`` — a 1-prefill + 1-decode :class:`DisaggregatedFleet`
      on a virtual clock, f32 KV blocks over the handoff channel;
    * ``disagg_int8`` — the same fleet on the int8 scale-per-block
      :class:`QuantizedPagedKVCache`;
    * ``disagg_int8_weights`` — int8 KV *and* int8 decode weights
      (``GPTConfig(weight_quant="int8")``): every replica quantizes
      its param tree once at init and decodes through the fused
      dequant-GEMM, reported with weight HBM bytes per replica and
      the kv+weight bytes each concurrent user pays.

    Reported per arm: wall tokens/s, KV bytes per user (measured from
    the live cache buffers, not the spec), token agreement vs the paged
    arm; the disagg arms add handoff count/bytes and simulated seconds
    on the virtual clock.  Agreement is MEASURED, not asserted: with 8
    requests over 4 slots the single engine plans prefill chunks while
    decodes are in flight, a different chunk partitioning (= XLA
    schedule) than the prefill-only pool's, and on a random-init
    near-flat-logits model last-ulp rounding flips argmax — the tier-1
    tests and the CI dryrun pin exact parity at the configs where the
    schedules match.  The headline extra is the int8/f32 handoff byte
    ratio — the series the CI leg gates at < 0.30."""
    import dataclasses

    from apex_tpu.inference import InferenceEngine, Request
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.serving import (DisaggregatedFleet, PagedInferenceEngine,
                                  TickScheduler, VirtualClock)
    from apex_tpu.utils.profiling import ServingMetrics

    _free_calibration()
    cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=2,
                    num_attention_heads=8, max_seq_len=128)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(11)
    sysp = list(rng.randint(1, cfg.vocab_size, 32))
    prompts = [(sysp if i % 2 == 0 else []) +
               list(rng.randint(1, cfg.vocab_size, 12))
               for i in range(8)]
    reqs = [Request(request_id=i, prompt=p, max_new_tokens=24)
            for i, p in enumerate(prompts)]

    def sched():
        return TickScheduler(token_budget=64, min_chunk=16, max_chunk=32)

    # int8-weight fleet arm: same f32 params in, the engine quantizes
    # once at init off the config knob
    qmodel = GPTModel(dataclasses.replace(cfg, weight_quant="int8"))

    def paged_engine(clock, quant=None, prefill_only=False, m=None):
        return PagedInferenceEngine(
            m or model, params, max_slots=4, block_size=16,
            chunked_prefill=True, scheduler=sched(), kv_quant=quant,
            prefill_only=prefill_only,
            metrics=ServingMetrics(clock), clock=clock)

    def fleet_arm(quant, m=None):
        clock = VirtualClock()
        # a 4-slot decode pool stays full for a whole 24-token decode:
        # let buffered handoffs wait for capacity instead of falling
        # back to re-prefill, so every request ships over the channel
        fleet = DisaggregatedFleet(
            [paged_engine(clock, quant, prefill_only=True, m=m)],
            [paged_engine(clock, quant, m=m)], clock=clock,
            handoff_retry_ticks=64)
        return fleet, clock

    def drive_engine(eng):
        for r in reqs:
            eng.submit(dataclasses.replace(r))
        out = eng.run()
        return ({r.request_id: r.tokens for r in out},
                sum(len(r.tokens) for r in out))

    def drive_fleet(fleet, clock):
        for r in reqs:
            fleet.submit(dataclasses.replace(r))
        for _ in range(2000):
            busy = fleet.step()
            clock.advance(0.01)
            if not busy and fleet.pending == 0:
                break
        out = fleet.completed
        return ({r.request_id: r.tokens for r in out},
                sum(len(r.tokens) for r in out))

    def paged_bytes_per_user(pool):
        # blocks a request's full sequence pins, ignoring prefix
        # sharing (the per-user worst case the capacity planner sizes)
        return pool.block_bytes * sum(
            pool.blocks_for(len(r.prompt) + r.max_new_tokens)
            for r in reqs) / len(reqs)

    arms = {}
    tokens_ref = None

    def agreement(toks):
        return sum(toks[i] == tokens_ref[i] for i in tokens_ref) \
            / len(tokens_ref)

    # -- single-engine arms ----------------------------------------------
    single = {
        "paged": lambda c: paged_engine(c),
        "contiguous": lambda c: InferenceEngine(
            model, params, max_slots=4, metrics=ServingMetrics(c),
            clock=c),
    }
    for name in ("paged", "contiguous"):       # paged first: the ref
        drive_engine(single[name](VirtualClock()))    # compile untimed

        def timed(name=name):
            clock = VirtualClock()
            eng = single[name](clock)
            t0 = time.perf_counter()
            toks, n = drive_engine(eng)
            dt = time.perf_counter() - t0
            if hasattr(eng, "pool"):
                per_user = paged_bytes_per_user(eng.pool)
            else:
                per_user = eng.cache.data.nbytes / eng.cache.data.shape[0]
            return toks, n, dt, per_user
        got = _retry(timed)
        if got is None:
            arms[name] = None
            continue
        toks, n, dt, per_user = got
        if tokens_ref is None:
            tokens_ref = toks
        arms[name] = {"tokens": n, "window_s": round(dt, 6),
                      "tokens_per_s": round(n / dt, 2),
                      "kv_bytes_per_user": round(per_user, 1),
                      "token_agreement": round(agreement(toks), 4)}

    # -- disaggregated arms ----------------------------------------------
    handoff_bytes = {}
    weight_bytes = {}
    for name, quant, m in (("disagg", None, None),
                           ("disagg_int8", "int8", None),
                           ("disagg_int8_weights", "int8", qmodel)):
        f0, c0 = fleet_arm(quant, m)
        drive_fleet(f0, c0)                    # compile untimed

        def timed(quant=quant, m=m):
            fleet, clock = fleet_arm(quant, m)
            t0 = time.perf_counter()
            toks, n = drive_fleet(fleet, clock)
            dt = time.perf_counter() - t0
            return toks, n, dt, fleet, clock
        got = _retry(timed)
        if got is None:
            arms[name] = None
            continue
        toks, n, dt, fleet, clock = got
        eng = fleet.decode.replicas[0]
        pool = eng.pool
        handoff_bytes[name] = fleet.channel.handoff_bytes
        weight_bytes[name] = eng.weight_bytes
        kv_per_user = paged_bytes_per_user(pool)
        arms[name] = {
            "tokens": n, "window_s": round(dt, 6),
            "tokens_per_s": round(n / dt, 2),
            "kv_bytes_per_user": round(kv_per_user, 1),
            "weight_bytes_per_replica": eng.weight_bytes,
            # weights amortize over the replica's concurrent users
            # (max_slots); KV is per user outright
            "kv_plus_weight_bytes_per_user": round(
                kv_per_user + eng.weight_bytes / 4, 1),
            "token_agreement": round(agreement(toks), 4),
            "handoffs": fleet.handoffs,
            "fallbacks": fleet.fallbacks,
            "handoff_bytes": fleet.channel.handoff_bytes,
            "sim_seconds": round(clock(), 4)}

    ratio = None
    if handoff_bytes.get("disagg") and handoff_bytes.get("disagg_int8"):
        ratio = round(handoff_bytes["disagg_int8"]
                      / handoff_bytes["disagg"], 4)
        assert ratio < 0.30, f"int8 handoff ratio {ratio} >= 0.30"
    wratio = None
    if weight_bytes.get("disagg") and weight_bytes.get("disagg_int8_weights"):
        wratio = round(weight_bytes["disagg_int8_weights"]
                       / weight_bytes["disagg"], 4)
        assert wratio < 0.30, \
            f"int8 weight byte ratio {wratio} >= 0.30"
    return {"arms": arms, "int8_handoff_byte_ratio": ratio,
            "int8_weight_byte_ratio": wratio}


def bench_lint():
    """Static-analysis leg (ISSUE 8): time the lint gate itself.

    Linting is compile-only and the gate is meant to ride in CI, so the
    metric is wall time per canonical program (<10 s each) plus the
    baseline diff.  The linter needs a multi-device CPU mesh (the
    canonical programs span dp/tp/pp), and this process owns the TPU —
    so drive ``tools/lint_graph.py`` in a subprocess pinned to the host
    platform, exactly as CI runs it."""
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "lint_graph.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)       # lint_graph sets its own device count
    t0 = time.perf_counter()
    out = subprocess.run([sys.executable, script, "--json"],
                         capture_output=True, text=True, env=env,
                         timeout=600)
    wall = time.perf_counter() - t0
    if out.returncode != 0:
        raise RuntimeError(
            f"lint gate failed (exit {out.returncode}): "
            f"{out.stderr[-1500:]}")
    doc = json.loads(out.stdout)
    per_program = {p["program"]: p["elapsed_s"] for p in doc["programs"]}
    slowest = max(per_program.values()) if per_program else 0.0
    return {"programs": len(per_program),
            "findings": sum(len(p["findings"]) for p in doc["programs"]),
            "new_findings": sum(len(v) for v in
                                doc.get("new_findings", {}).values()),
            "per_program_s": {k: round(v, 3)
                              for k, v in per_program.items()},
            "slowest_program_s": round(slowest, 3),
            "per_program_target_s": 10.0,
            "per_program_ok": bool(slowest < 10.0),
            "total_wall_s": round(wall, 3)}


def bench_autotune():
    """Auto-parallel planner leg (ISSUE 11): predicted-vs-measured gap.

    Runs ``tools/autotune.py`` end-to-end on a small GPT over an
    8-device CPU mesh — enumerate, memory-prune, cost-model rank,
    measure top-3 — and reports how far the cost model's predictions
    sit from the wall clock it then measured.  The planner owns its own
    mesh and this process owns the TPU, so it rides in a subprocess
    pinned to the host platform, exactly as CI runs it."""
    import subprocess
    import sys
    import tempfile

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "autotune.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmp:
        out_path = os.path.join(tmp, "autotune_plan.json")
        out = subprocess.run(
            [sys.executable, script, "--devices", "8", "--out", out_path,
             "--max-tp", "2", "--max-pp", "2", "--no-zero", "--no-remat",
             "--quiet"],
            capture_output=True, text=True, env=env, timeout=1200)
        if out.returncode != 0:
            raise RuntimeError(
                f"autotune failed (exit {out.returncode}): "
                f"{out.stderr[-1500:]}")
        with open(out_path) as f:
            report = json.load(f)
    wall = time.perf_counter() - t0
    measured = [c for c in report["candidates"]
                if c.get("measured_s") is not None]
    # the gap the cost model owes the user: per measured candidate,
    # |predicted - measured| / measured
    gaps = [abs(c["predicted_s"] - c["measured_s"]) / c["measured_s"]
            for c in measured]
    ranked = sorted((c for c in report["candidates"]
                     if c.get("predicted_s") is not None),
                    key=lambda c: c["predicted_s"])
    pred_best = ranked[0]["plan"] if ranked else None
    meas_best = min(measured, key=lambda c: c["measured_s"]) if measured \
        else None
    return {"candidates": len(report["candidates"]),
            "measured": len(measured),
            "winner": report["plan"],
            "predicted_s": report.get("predicted_s"),
            "measured_s": report.get("measured_s"),
            "gap_mean": round(sum(gaps) / len(gaps), 4) if gaps else None,
            "gap_max": round(max(gaps), 4) if gaps else None,
            "predicted_best_is_measured_best": bool(
                pred_best is not None and meas_best is not None
                and pred_best == meas_best["plan"]),
            "total_wall_s": round(wall, 3)}


def bench_mpmd():
    """Cross-pod MPMD schedule leg (ISSUE 14): how much of a slow DCN
    hop each schedule hides.

    Prices classic 1F1B under blocking sends (the lockstep/SPMD model:
    every inter-pod hop sits on the critical path) against the
    ``dcn_hiding`` schedule under asynchronous sends (the MPMD host
    model: extra in-flight microbatches buffer the hop) with the
    ``apex_tpu.mpmd.schedule.simulate`` event model — 4 stages split
    across 2 pods, the DCN edge costing ~half a forward.  Pure host
    arithmetic (no devices), so the recorded bubble fractions are
    deterministic across rounds and ``bench_diff``-able; the MPMD
    engine's numerics ride the tier-1 gate
    (``__graft_entry__._dryrun_mpmd``), not this leg."""
    from apex_tpu.mpmd.schedule import (SCHEDULES, edge_link_classes,
                                        simulate)

    S, M, pods = 4, 8, 2
    t_fwd, t_bwd = 1.0, 2.0
    classes = edge_link_classes(S, pods)
    rows = {}
    for dcn_s in (0.0, 1.5):
        link = {e: (dcn_s if lc == "dcn" else 0.05)
                for e, lc in classes.items()}
        for name in ("1f1b", "dcn_hiding"):
            sim = simulate(SCHEDULES[name](S, M), S, M, t_fwd=t_fwd,
                           t_bwd=t_bwd, link_seconds=link,
                           link_classes=classes,
                           blocking_sends=(name == "1f1b"))
            rows[f"{name}_dcn{dcn_s:g}"] = {
                "makespan": round(sim["makespan"], 3),
                "bubble_fraction": round(sim["bubble_fraction"], 4),
                "dcn_hidden_fraction": round(
                    sim["hidden_fraction"]["dcn"], 4),
            }
    slow_base = rows["1f1b_dcn1.5"]
    slow_tuned = rows["dcn_hiding_dcn1.5"]
    return {
        "stages": S, "microbatches": M, "pods": pods,
        "t_fwd": t_fwd, "t_bwd": t_bwd, "dcn_link_s": 1.5,
        "schedules": rows,
        "bubble_reduction_vs_1f1b": round(
            slow_base["bubble_fraction"] - slow_tuned["bubble_fraction"],
            4),
        "speedup_vs_1f1b": round(
            slow_base["makespan"] / slow_tuned["makespan"], 4),
        "dcn_tuned_wins": bool(
            slow_tuned["bubble_fraction"] < slow_base["bubble_fraction"]),
    }


def bench_fused_ffn():
    """Fused-FFN leg (ISSUE 17): the Pallas fused bias-GELU FFN pair vs
    the unfused XLA chain, fwd+bwd at the BERT-large headline FFN shape
    (16x512 tokens, 1024 -> 4096 -> 1024, bf16).

    On TPU the fused arm runs the kernel and the speedup prices the
    HBM round-trip of the ``(tokens, ffn_hidden)`` activation the
    unfused chain pays between its two GEMMs.  Off-TPU the fused arm
    dispatches to the bitwise unfused reference, so the speedup
    honestly reads ~1.0 — the recorded ``path`` says which arm ran;
    tiling sweeps live in ``tools/sweep_ffn.py``."""
    from apex_tpu.ops.fused_ffn import fused_ffn, fused_ffn_reference
    from apex_tpu.utils import use_pallas

    m, h, f = 16 * 512, 1024, 4096
    rng = np.random.RandomState(0)
    bf = jnp.bfloat16
    x = jnp.asarray(rng.randn(m, h), bf)
    w1 = jnp.asarray(rng.randn(f, h) * 0.02, bf)
    b1 = jnp.asarray(rng.randn(f) * 0.02, bf)
    w2 = jnp.asarray(rng.randn(h, f) * 0.02, bf)
    b2 = jnp.asarray(rng.randn(h) * 0.02, bf)
    args = (x, w1, b1, w2, b2)

    def grad_of(ffn):
        def loss(*a):
            return jnp.sum(ffn(*a).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3, 4)))

    t_unfused = _time_steps(grad_of(fused_ffn_reference), args,
                            warmup=2, iters=8, rounds=3)
    jax.clear_caches()
    t_fused = _time_steps(grad_of(fused_ffn), args,
                          warmup=2, iters=8, rounds=3)
    jax.clear_caches()
    out = {"tokens": m, "hidden": h, "ffn_hidden": f,
           "dtype": "bfloat16",
           "path": "pallas" if use_pallas() else "reference",
           "unfused_s": round(t_unfused, 6),
           "fused_s": round(t_fused, 6)}
    # off-TPU both arms run the same unfused reference, so the ratio is
    # pure dispatch noise — record it under an ``_advisory`` key so
    # bench_diff never flags a phantom regression on CPU rounds
    key = "speedup" if use_pallas() else "speedup_advisory"
    out[key] = round(t_unfused / t_fused, 4)
    return out


def bench_mfu_multichip():
    """Multi-chip MFU leg (ISSUE 17): per-chip achieved FLOPs and MFU
    for dp x tp train steps with the fused-FFN knob on, plus the
    autotune planner's predicted-vs-measured gap at those plans.

    Runs ``tools/mfu_multichip.py`` over an 8-device host mesh in a
    subprocess pinned to the host platform (this process owns the TPU;
    the tool owns its mesh — the ``bench_autotune`` idiom).  The MFU
    denominator is the same calibrated matmul roofline the planner
    ranks with, so the fraction is honest on CPU hosts too — but on a
    CPU host that calibration drifts double-digit percent run-to-run
    with machine load, so the ratio is incomparable across rounds
    (r07->r08 measured achieved-flops UP 20% while "mfu" fell 11%
    purely on a faster calibration): off-TPU the ``mfu`` keys are
    recorded as ``mfu_advisory`` so bench_diff never flags a phantom
    regression; the achieved-flops and predicted-vs-measured ``gap``
    series remain the gated trend."""
    import subprocess
    import sys
    import tempfile

    from apex_tpu.utils import use_pallas

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "mfu_multichip.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmp:
        out_path = os.path.join(tmp, "mfu_multichip.json")
        out = subprocess.run(
            [sys.executable, script, "--devices", "8", "--out", out_path,
             "--quiet"],
            capture_output=True, text=True, env=env, timeout=1200)
        if out.returncode != 0:
            raise RuntimeError(
                f"mfu_multichip failed (exit {out.returncode}): "
                f"{out.stderr[-1500:]}")
        with open(out_path) as f:
            report = json.load(f)
    if not use_pallas():
        report["mfu_advisory"] = report.pop("mfu", None)
        for row in report.get("rows", {}).values():
            if "mfu" in row:
                row["mfu_advisory"] = row.pop("mfu")
    report["total_wall_s"] = round(time.perf_counter() - t0, 3)
    return report


def bench_anatomy():
    """Step-anatomy leg (ISSUE 20): what the measured critical-path
    profiler costs and whether its attribution stays exact.

    Three parts.  (1) A deterministic synthetic core: simulate a
    4-stage/8-microbatch 1F1B schedule with a slow DCN edge,
    synthesize its trace events, reconstruct + attribute, and
    self-diff against the generating simulation — the attribution
    must sum to the makespan exactly, per-op ratios must cover every
    op, and the self-diff drift must be ~0 (pure host arithmetic, so
    the recorded fractions are bench_diff-able across rounds).
    (2) The paired-window trace-overhead gate: the SAME dp2 x pp2
    ``MpmdPipeline`` step run bare vs ``trace=True`` back-to-back,
    median per-pass ratio, < 2% target — the established
    observability-leg protocol.  (3) One ``measure_ops=True`` step
    reconstructed and attributed for real (wall numbers advisory:
    host-serial dispatch on a shared CPU is honest but noisy)."""
    from apex_tpu.mpmd.schedule import (SCHEDULES, edge_link_classes,
                                        simulate)
    from apex_tpu.observability.anatomy import (
        CATEGORIES, attribute, diff_timelines, reconstruct,
        synthesize_events)

    S, M, pods = 4, 8, 2
    classes = edge_link_classes(S, pods)
    link = {e: (1.5 if lc == "dcn" else 0.05)
            for e, lc in classes.items()}
    order = SCHEDULES["1f1b"](S, M)
    sim = simulate(order, S, M, t_fwd=1.0, t_bwd=2.0,
                   link_seconds=link, link_classes=classes,
                   blocking_sends=False)
    evs = synthesize_events(sim, n_stages=S, n_microbatches=M)
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        tl = reconstruct(evs)
        attr = attribute(tl)
    anat_s = (time.perf_counter() - t0) / reps
    err = max(abs(st["total"] - attr["makespan"])
              for st in attr["per_stage"]) / attr["makespan"]
    self_diff = diff_timelines(tl, sim)
    out = {
        "stages": S, "microbatches": M, "events": len(evs),
        "reconstruct_attribute_s_advisory": round(anat_s, 6),
        "attribution_rel_err": float(err),
        "attribution_exact": bool(err < 1e-9),
        "fractions": {c: round(attr["fractions"][c], 4)
                      for c in CATEGORIES},
        "self_drift_score": round(self_diff["drift_score"], 6),
        "ratios_cover_all_ops": bool(
            len(self_diff["ratios"]) == 2 * S * M),
    }

    n = len(jax.devices())
    if n < 4:
        out["engine"] = {"skipped": "needs >= 4 devices"}
        return out
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.mpmd import MpmdPipeline
    from apex_tpu.parallel.plan import ParallelPlan

    _free_calibration()
    kw = dict(vocab_size=256, hidden_size=64, num_layers=4,
              num_attention_heads=4, max_seq_len=32)
    model = GPTModel(GPTConfig(**kw))
    params = model.init_params(jax.random.PRNGKey(0))
    plan = ParallelPlan(dp=2, pp=2, n_pods=2, n_microbatches=4)
    devs = jax.devices()[:4]
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 256, (2 * 4 * 2, 32)))
    targets = jnp.asarray(rng.randint(0, 256, (2 * 4 * 2, 32)))
    bare = MpmdPipeline(kw, params, plan, devices=devs)
    traced = MpmdPipeline(kw, params, plan, devices=devs, trace=True)

    def run_bare(tk, tg):
        return bare.loss_and_grads(tk, tg, step=0)[0]

    def run_traced(tk, tg):
        for tr in traced.tracers:   # bound the event buffers
            tr.clear()
        return traced.loss_and_grads(tk, tg, step=0)[0]

    # paired windows: time bare and traced back-to-back each pass,
    # headline is the median per-pass ratio (the < 2% protocol of
    # bench_observability); a ~60ms host-serial step needs wide
    # windows and several passes for the median to beat shared-host
    # scheduler noise down to the gate's resolution
    passes = []
    for _ in range(9):
        t_b = _time_steps(run_bare, (tokens, targets), warmup=1,
                          iters=10, rounds=1)
        t_t = _time_steps(run_traced, (tokens, targets), warmup=1,
                          iters=10, rounds=1)
        passes.append((t_b, t_t))
    passes.sort(key=lambda p: p[1] / p[0])
    t_b, t_t = passes[len(passes) // 2]
    overhead = t_t / t_b - 1.0
    out["engine"] = {
        "bare_step_s_advisory": round(t_b, 6),
        "traced_step_s_advisory": round(t_t, 6),
        "trace_overhead_frac": round(overhead, 4),
        "trace_overhead_target": 0.02,
        "trace_overhead_ok": bool(overhead < 0.02),
    }

    # one honest measured step: block on every op, reconstruct,
    # attribute, diff against the schedule priced at measured medians
    anat = MpmdPipeline(kw, params, plan, devices=devs,
                        measure_ops=True)
    anat.loss_and_grads(tokens, targets, step=0)     # compile warmup
    for tr in anat.tracers:
        tr.clear()
    anat.loss_and_grads(tokens, targets, step=1)
    tl_r = reconstruct(anat.anatomy_events())
    attr_r = attribute(tl_r)
    err_r = max(abs(st["total"] - attr_r["makespan"])
                for st in attr_r["per_stage"]) / attr_r["makespan"]

    med = lambda xs: sorted(xs)[len(xs) // 2] if xs else 1e-6
    durs = {"fwd": [], "bwd": []}
    for o in tl_r.ops:
        durs[o["kind"]].append(o["end"] - o["start"])
    by_edge = {}
    for x in tl_r.xfers:
        if x["mb"] >= 0:
            by_edge.setdefault(min(x["src"], x["dst"]), []).append(
                x["end"] - x["start"])
    sim_r = simulate(anat.order, 2, 4,
                     t_fwd=med(durs["fwd"]) or med(durs["bwd"]),
                     t_bwd=med(durs["bwd"]),
                     link_seconds={e: med(ts)
                                   for e, ts in by_edge.items()},
                     link_classes=edge_link_classes(2, 2),
                     blocking_sends=False)
    d_r = diff_timelines(tl_r, sim_r, fold_last_fwd=True)
    out["measured"] = {
        "makespan_s_advisory": round(tl_r.makespan, 6),
        "n_ops": len(tl_r.ops),
        "attribution_rel_err": float(err_r),
        "attribution_exact": bool(err_r < 1e-9),
        "ratios_cover_all_ops": bool(
            d_r["matched"] == d_r["n_ops"] == len(tl_r.ops)),
        # real wall seconds on a shared host: advisory per key so a
        # noisy round never flags a phantom component regression —
        # the *.anatomy.json sidecar carries these for bench_diff's
        # attribution-delta printing instead
        **{f"{c}_s_advisory": round(attr_r["totals"][c], 6)
           for c in CATEGORIES},
        "drift_score_advisory": round(d_r["drift_score"], 4),
        **{f"{c}_frac_advisory": round(attr_r["fractions"][c], 4)
           for c in CATEGORIES},
    }
    return out


def _extra_legs():
    """Leg name (as it appears under the result's ``extra``) -> bench
    function, for ``--legs`` subset runs."""
    return {
        "bert_large_lamb": bench_bert_lamb_train_step,
        "breakdown": bench_bert_breakdown,
        "lamb_in_step": bench_lamb_in_step,
        "gpt": bench_gpt_train_step,
        "gpt_decode": bench_gpt_decode,
        "fused_adam_vs_optax": bench_fused_adam_vs_optax,
        "dp_comm": bench_dp_comm,
        "tp_overlap": bench_tp_overlap,
        "pp_schedules": bench_pp_schedules,
        "resilience": bench_resilience,
        "elastic": bench_elastic,
        "capacity": bench_capacity,
        "autopilot": bench_autopilot,
        "observability": bench_observability,
        "serving_observability": bench_serving_observability,
        "serving_paged": bench_serving_paged,
        "serving_chaos": bench_serving_chaos,
        "serving_disagg": bench_serving_disagg,
        "lint": bench_lint,
        "autotune": bench_autotune,
        "mpmd": bench_mpmd,
        "anatomy": bench_anatomy,
        "fused_ffn": bench_fused_ffn,
        "mfu_multichip": bench_mfu_multichip,
    }


def _headline_of(leg_name: str, leg: dict):
    """A representative (metric, value) for a subset run's headline:
    the first ``tokens_per_s`` / ``mfu`` / ``speedup`` leaf, else the
    first numeric leaf."""
    def flat(d, pre=""):
        for k, v in d.items():
            if isinstance(v, dict):
                yield from flat(v, f"{pre}{k}.")
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                yield f"{pre}{k}", float(v)
    pairs = list(flat(leg))
    for pat in ("tokens_per_s", "mfu", "speedup"):
        for k, v in pairs:
            if pat in k:
                return f"{leg_name}.{k}", v
    if pairs:
        return f"{leg_name}.{pairs[0][0]}", pairs[0][1]
    return leg_name, 0.0


def _main_subset(names):
    """Run only the named extra legs (no headline BERT leg) and print
    the same one-line JSON shape ``main()`` does, headlined by the
    first leg's primary metric."""
    table = _extra_legs()
    unknown = [n for n in names if n not in table]
    if unknown:
        raise SystemExit(f"unknown legs: {unknown}; "
                         f"choose from {sorted(table)}")
    extra = {"backend": jax.default_backend(),
             "device_kind": jax.devices()[0].device_kind}
    for n in names:
        extra[n] = _retry(table[n])
    first = next((n for n in names if extra[n] is not None), None)
    if first is None:
        raise RuntimeError("every requested leg failed")
    metric, value = _headline_of(first, extra[first])
    print(json.dumps({"metric": metric, "value": round(value, 4),
                      "unit": "per_leg", "legs": names, "extra": extra}))


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="apex_tpu bench: one JSON line on stdout")
    ap.add_argument("--legs", default=None,
                    help="comma-separated subset of extra legs to run "
                         "(e.g. serving_disagg,serving_paged); the "
                         "headline BERT leg and every unlisted leg are "
                         "skipped, and the first listed leg's primary "
                         "metric becomes the headline")
    args = ap.parse_args(argv)
    if args.legs is not None:
        return _main_subset([s for s in args.legs.split(",") if s])
    backend = jax.default_backend()
    # every leg's result also lands on the metrics registry as one
    # `bench_leg` JSONL record (ISSUE 5) — BENCH output carries a
    # `metrics_stream` pointer to the stream file
    from apex_tpu.observability import MetricsRegistry

    stream_path = os.environ.get("APEX_TPU_METRICS_STREAM",
                                 "bench_metrics.jsonl")
    registry = MetricsRegistry()
    try:
        registry.open_stream(stream_path)
    except OSError:
        stream_path = None
    # headline leg is hard-required (retried, then raises); auxiliary
    # legs degrade to null on repeated transient tunnel failures
    bert = _retry(bench_bert_lamb_train_step)
    if bert is None:
        raise RuntimeError("headline BERT leg failed after retries")
    gpt = _retry(bench_gpt_train_step)
    decode = _retry(bench_gpt_decode)
    breakdown = _retry(bench_bert_breakdown)
    in_step = _retry(bench_lamb_in_step)
    adam = _retry(bench_fused_adam_vs_optax)
    dp_comm = _retry(bench_dp_comm)
    tp_overlap = _retry(bench_tp_overlap)
    pp_schedules = _retry(bench_pp_schedules)
    resilience = _retry(bench_resilience)
    elastic = _retry(bench_elastic)
    capacity = _retry(bench_capacity)
    autopilot = _retry(bench_autopilot)
    observability = _retry(bench_observability)
    serving_obs = _retry(bench_serving_observability)
    serving_paged = _retry(bench_serving_paged)
    serving_chaos = _retry(bench_serving_chaos)
    serving_disagg = _retry(bench_serving_disagg)
    lint_gate = _retry(bench_lint)
    autotune_leg = _retry(bench_autotune)
    mpmd = _retry(bench_mpmd)
    anatomy = _retry(bench_anatomy)
    fused_ffn_leg = _retry(bench_fused_ffn)
    mfu_multichip = _retry(bench_mfu_multichip)
    rounded = lambda d: (None if d is None else
                         {k: (round(v, 6) if isinstance(v, float) else v)
                          for k, v in d.items()})
    # headline = the binding BASELINE.md row-1 workload (BERT-large +
    # FusedLAMB + amp O2); the GPT and optimizer legs ride in `extra`
    result = {
        "metric": "bert_large_lamb_mfu",
        "value": round(bert["mfu"], 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(bert["mfu"] / 0.5, 4),  # >=50% MFU target
        "extra": {
            "backend": backend,
            "device_kind": jax.devices()[0].device_kind,
            "bert_large_lamb": rounded(bert),
            "breakdown": breakdown,
            "lamb_in_step": in_step,
            "gpt_350m_train_mfu": None if gpt is None else round(
                gpt["mfu"], 4),
            "gpt": rounded(gpt),
            "gpt_decode": rounded(decode),
            "fused_adam_vs_optax": rounded(adam),
            "dp_comm": dp_comm,
            "tp_overlap": tp_overlap,
            "pp_schedules": pp_schedules,
            "resilience": resilience,
            "elastic": elastic,
            "capacity": capacity,
            "autopilot": autopilot,
            "observability": rounded(observability),
            "serving_observability": rounded(serving_obs),
            "serving_paged": serving_paged,
            "serving_chaos": serving_chaos,
            "serving_disagg": serving_disagg,
            "lint": lint_gate,
            "autotune": autotune_leg,
            "mpmd": mpmd,
            "anatomy": anatomy,
            "fused_ffn": fused_ffn_leg,
            "mfu_multichip": mfu_multichip,
        },
    }
    result["metrics_stream"] = stream_path
    if stream_path is not None:
        g_mfu = registry.gauge("bench_bert_mfu",
                               "headline BERT-large MFU (spec)")
        g_mfu.set(bert["mfu"])
        for leg, res in result["extra"].items():
            if isinstance(res, dict):
                registry.event("bench_leg", leg=leg, result=res)
        registry.close()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
