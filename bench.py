"""apex_tpu benchmark — run on the real TPU chip, print ONE JSON line.

Measures the two binding BASELINE.md metrics that are measurable on a
single chip:

* GPT (350M-class) fwd+bwd+FusedAdam step -> tokens/s and MFU vs the
  chip's peak bf16 FLOPs (north star: >=50% MFU at pod scale).
* FusedAdam packed-bucket step vs unfused optax adam on the same params
  -> speedup (the core premise of the multi-tensor engine).

The headline metric is MFU; everything else rides in "extra".
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# peak dense bf16 FLOPs/s per chip by device kind (public spec sheets)
_PEAK_BF16 = {
    "TPU v5 lite": 197e12,       # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,            # v5p
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,       # v6e / Trillium
    "TPU v6e": 918e12,
}


def _peak_flops() -> float:
    kind = jax.devices()[0].device_kind
    for k, v in _PEAK_BF16.items():
        if kind.startswith(k):
            return v
    return 197e12  # conservative default


def _time_steps(fn, args, warmup=2, iters=8):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_gpt_train_step():
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_attention_heads=16, max_seq_len=1024,
                    dtype=jnp.bfloat16)
    # batch is HBM-bound until flash attention lands: the materialized
    # (b*h, s, s) scores+probs dominate at ~1.5 GB/batch-row for 24 layers
    batch, seq = 2, 1024
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    adam = FusedAdam(lr=1e-4)
    opt_state = adam.init(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    @jax.jit
    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens,
                                                     targets)
        new_params, new_opt = adam.step(grads, params, opt_state)
        return loss, new_params, new_opt

    # steady-state timing with state threading (donation-free but honest)
    def run(params, opt_state, tokens, targets):
        return train_step(params, opt_state, tokens, targets)

    dt = _time_steps(run, (params, opt_state, tokens, targets))
    tokens_per_s = batch * seq / dt
    # PaLM-style accounting: 6*N per token (fwd+bwd) + attention term
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size \
        * seq
    mfu = tokens_per_s * flops_per_token / _peak_flops()
    return {
        "n_params": n_params,
        "step_time_s": dt,
        "tokens_per_s": tokens_per_s,
        "mfu": mfu,
    }


def bench_fused_adam_vs_optax():
    import optax

    from apex_tpu.optimizers import FusedAdam

    rng = np.random.RandomState(1)
    shapes = []
    # BERT-large-ish param census: many embeddings/matrices/vectors
    for _ in range(24):
        shapes += [(1024, 1024), (4096, 1024), (1024, 4096),
                   (1024,), (4096,), (1024,), (1024,)]
    shapes += [(30522, 1024), (512, 1024)]
    params = [jnp.asarray(rng.randn(*s).astype(np.float32) * 0.02)
              for s in shapes]
    grads = [jnp.asarray(rng.randn(*s).astype(np.float32) * 1e-3)
             for s in shapes]

    fused = FusedAdam(lr=1e-3)
    fstate = fused.init(params)

    @jax.jit
    def fused_step(grads, params, state):
        return fused.step(grads, params, state)

    opt = optax.adam(1e-3)
    ostate = opt.init(params)

    @jax.jit
    def optax_step(grads, params, state):
        updates, new_state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state

    t_fused = _time_steps(fused_step, (grads, params, fstate))
    t_optax = _time_steps(optax_step, (grads, params, ostate))
    return {
        "n_tensors": len(shapes),
        "n_elements": int(sum(int(np.prod(s)) for s in shapes)),
        "fused_step_s": t_fused,
        "optax_step_s": t_optax,
        "speedup": t_optax / t_fused,
    }


def main():
    backend = jax.default_backend()
    gpt = bench_gpt_train_step()
    adam = bench_fused_adam_vs_optax()
    result = {
        "metric": "gpt_350m_train_mfu",
        "value": round(gpt["mfu"], 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(gpt["mfu"] / 0.5, 4),   # >=50% MFU target
        "extra": {
            "backend": backend,
            "device_kind": jax.devices()[0].device_kind,
            "gpt": {k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in gpt.items()},
            "fused_adam_vs_optax": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in adam.items()},
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
